"""Sparsity-adaptive device residency (sorted-array containers).

Three layers, mirroring the dense suite's structure:

1. Kernel differential — the host sorted-array reference, the XLA
   gather ladder (bitops.sparse_pair_intersect_counts), and the Pallas
   kernel in interpret mode (kernels.pallas_sparse_pair_counts) must
   agree bit-exact on every container boundary the roaring format has:
   empty, singleton, full 4096-value arrays, the 0/65535 edges, and the
   0xFFFF padding collision.
2. Format pick — pick_slice_formats unit behavior: threshold, the
   ARRAY_VALUE_CAP and SPARSE_MIN_SLICE_CARD eligibility gates, and the
   hysteresis band that keeps boundary slices from flapping layouts.
3. Serving — end-to-end Executor counts on sparse and mixed views
   (device vs host, per-slice fallback poisoned so only the mesh path
   can answer), demote-to-dense for shapes the sparse kernels don't
   serve, the residency gauge, and mixed-format eviction under a
   sub-working-set HBM budget.
"""

import numpy as np
import pytest

from pilosa_tpu import SLICE_WIDTH
from pilosa_tpu.core import Holder
from pilosa_tpu.executor import Executor
from pilosa_tpu.pql import parse_string


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


def q(executor, index, pql):
    return executor.execute(index, parse_string(pql))


def poison_per_slice(monkeypatch):
    """Make the per-slice host fallback unusable so a passing query
    proves the device path served it."""
    from pilosa_tpu.parallel.plan import CountPlan

    def boom(self, slice_):
        raise AssertionError("per-slice path used; device path expected")

    monkeypatch.setattr(CountPlan, "count_slice", boom)


def seed_sparse(holder, frame, rows=(1, 2), per_slice=1500, slices=2,
                seed=7, spread=3):
    """Rows of ~per_slice values over `spread` containers per slice —
    above the SPARSE_MIN_SLICE_CARD floor, under the 5% density
    threshold and the 4096-value array cap, so the stager picks the
    sorted-array format."""
    idx = holder.create_index_if_not_exists("i")
    f = idx.create_frame_if_not_exists(frame)
    rng = np.random.default_rng(seed)
    for row in rows:
        for s in range(slices):
            cols = rng.choice(spread * 65536, size=per_slice,
                              replace=False) + s * SLICE_WIDTH
            for c in cols:
                f.set_bit(row, int(c))
    return f


def seed_dense(holder, frame, rows=(1, 2), slices=2, seed=11):
    """Rows with an 8000-value container per slice: max_card over the
    4096 array cap, so the stager keeps packed words."""
    idx = holder.create_index_if_not_exists("i")
    f = idx.create_frame_if_not_exists(frame)
    rng = np.random.default_rng(seed)
    for row in rows:
        for s in range(slices):
            cols = rng.choice(12000, size=8000,
                              replace=False) + s * SLICE_WIDTH
            for c in cols:
                f.set_bit(row, int(c))
    return f


# -- 1. kernel differential ---------------------------------------------------


def _pad_pool(arrays, k=None):
    """(C, K) int32 pool + (C,) lens from a list of sorted value
    arrays, 0xFFFF padded — the exact layout the stager builds."""
    if k is None:
        k = max((len(a) for a in arrays), default=1)
        k = max(8, -(-k // 8) * 8)
    vals = np.full((len(arrays), k), 0xFFFF, dtype=np.int32)
    lens = np.zeros(len(arrays), dtype=np.int32)
    for i, a in enumerate(arrays):
        a = np.asarray(sorted(a), dtype=np.int32)
        vals[i, : len(a)] = a
        lens[i] = len(a)
    return vals, lens


BOUNDARY_CONTAINERS = [
    [],                                   # empty
    [0],                                  # singleton at the low edge
    [65535],                              # singleton at the pad value
    [7],                                  # singleton, interior
    list(range(4096)),                    # full array container
    list(range(0, 65536, 16)),            # spread 4096-value container
    list(range(61440, 65536)),            # full container at high edge
    [0, 1, 2, 3, 65532, 65533, 65534, 65535],  # both edges
    list(range(100, 200)),                # small interior run
]


class TestSparseKernelDifferential:
    def _pairs(self):
        """Every boundary container against every other (including
        itself) plus random duplicates-free draws."""
        rng = np.random.default_rng(3)
        cs = list(BOUNDARY_CONTAINERS)
        for n in (1, 100, 2048, 4096):
            cs.append(sorted(rng.choice(65536, size=n, replace=False)))
        a_list, b_list = [], []
        for a in cs:
            for b in cs:
                a_list.append(a)
                b_list.append(b)
        return a_list, b_list

    def test_pair_xla_vs_host_vs_pallas_interpret(self):
        from pilosa_tpu.ops.bitops import (sparse_pair_count_host,
                                           sparse_pair_intersect_counts)
        from pilosa_tpu.ops.kernels import pallas_sparse_pair_counts

        a_list, b_list = self._pairs()
        a_vals, a_len = _pad_pool(a_list)
        b_vals, b_len = _pad_pool(b_list)
        want = np.array([sparse_pair_count_host(a, b)
                         for a, b in zip(a_list, b_list)], dtype=np.int32)
        got_xla = np.asarray(
            sparse_pair_intersect_counts(a_vals, a_len, b_vals, b_len))
        np.testing.assert_array_equal(got_xla, want)
        got_pl = np.asarray(pallas_sparse_pair_counts(
            a_vals, a_len, b_vals, b_len, interpret=True))
        np.testing.assert_array_equal(got_pl, want)

    def test_pair_asymmetric_value_caps(self):
        """Operands from pools with different K paddings (a mixed
        sd-vs-ss staging) must still agree."""
        from pilosa_tpu.ops.bitops import (sparse_pair_count_host,
                                           sparse_pair_intersect_counts)
        from pilosa_tpu.ops.kernels import pallas_sparse_pair_counts

        rng = np.random.default_rng(5)
        a_list = [sorted(rng.choice(65536, size=n, replace=False))
                  for n in (0, 1, 60, 64)]
        b_list = [sorted(rng.choice(65536, size=n, replace=False))
                  for n in (4096, 3000, 1, 0)]
        a_vals, a_len = _pad_pool(a_list, k=64)
        b_vals, b_len = _pad_pool(b_list, k=4096)
        want = np.array([sparse_pair_count_host(a, b)
                         for a, b in zip(a_list, b_list)], dtype=np.int32)
        np.testing.assert_array_equal(
            np.asarray(sparse_pair_intersect_counts(
                a_vals, a_len, b_vals, b_len)), want)
        np.testing.assert_array_equal(
            np.asarray(pallas_sparse_pair_counts(
                a_vals, a_len, b_vals, b_len, interpret=True)), want)

    def test_probe_array_x_bitmap(self):
        """The mixed array×bitmap probe vs a host membership check."""
        from pilosa_tpu.ops.bitops import sparse_probe_intersect_counts
        from pilosa_tpu.ops.pool import CONTAINER_WORDS

        rng = np.random.default_rng(9)
        a_list = list(BOUNDARY_CONTAINERS)
        a_vals, a_len = _pad_pool(a_list)
        words = np.zeros((len(a_list), CONTAINER_WORDS), dtype=np.uint32)
        for i in range(len(a_list)):
            bits = rng.choice(65536, size=rng.integers(0, 20000),
                              replace=False)
            np.bitwise_or.at(words[i], bits >> 5,
                             np.uint32(1) << (bits & 31).astype(np.uint32))
        want = []
        for i, a in enumerate(a_list):
            a = np.asarray(a, dtype=np.int64)
            if not a.size:
                want.append(0)
                continue
            hit = (words[i][a >> 5] >> (a & 31).astype(np.uint32)) & 1
            want.append(int(hit.sum()))
        got = np.asarray(sparse_probe_intersect_counts(
            a_vals, a_len, words))
        np.testing.assert_array_equal(got, np.array(want, dtype=np.int32))

    def test_op_identities_match_set_ops(self):
        """The inclusion–exclusion identities the serving path uses
        must reproduce real set-op cardinalities."""
        from pilosa_tpu.ops.bitops import (sparse_pair_count_host,
                                           sparse_op_counts)

        rng = np.random.default_rng(13)
        for _ in range(20):
            a = set(map(int, rng.choice(65536, size=rng.integers(0, 3000))))
            b = set(map(int, rng.choice(65536, size=rng.integers(0, 3000))))
            inter = sparse_pair_count_host(sorted(a), sorted(b))
            assert sparse_op_counts("and", inter, len(a), len(b)) \
                == len(a & b)
            assert sparse_op_counts("or", inter, len(a), len(b)) \
                == len(a | b)
            assert sparse_op_counts("andnot", inter, len(a), len(b)) \
                == len(a - b)
            assert sparse_op_counts("xor", inter, len(a), len(b)) \
                == len(a ^ b)


# -- 2. format pick -----------------------------------------------------------


class TestFormatPick:
    def _stats(self, rows):
        return np.array(rows, dtype=np.int64)

    def test_threshold_and_eligibility(self):
        from pilosa_tpu.parallel.mesh import pick_slice_formats

        stats = self._stats([
            (16, 2000, 200),     # 0.19% fill -> sparse
            (16, 60000, 5000),   # a container over the 4096 cap -> dense
            (1, 60000, 60000),   # can't happen (cap 4096) but: dense
            (0, 0, 0),           # empty slice -> dense
            (16, 500, 40),       # under the min-card floor -> dense
            (2, 130000, 4096),   # ~99% fill -> dense
        ])
        fmt = pick_slice_formats(stats, 0.05)
        np.testing.assert_array_equal(fmt, [1, 0, 0, 0, 0, 0])

    def test_kill_switch(self):
        from pilosa_tpu.parallel.mesh import pick_slice_formats

        stats = self._stats([(16, 2000, 200)])
        np.testing.assert_array_equal(pick_slice_formats(stats, 0.0), [0])
        np.testing.assert_array_equal(pick_slice_formats(stats, -1), [0])

    def test_hysteresis_keeps_boundary_slice(self):
        from pilosa_tpu.parallel.mesh import pick_slice_formats

        # density = total / (n * 65536); threshold 5%, band 1.25:
        # keep-sparse window is [5%, 6.25%), go-sparse needs < 4%.
        n = 16
        d_in_band = int(n * 65536 * 0.055)   # 5.5%: inside the band
        stats = self._stats([(n, d_in_band, 4000)])
        # fresh pick at 5.5%: dense
        np.testing.assert_array_equal(pick_slice_formats(stats, 0.05), [0])
        # was sparse: the band keeps it sparse
        np.testing.assert_array_equal(
            pick_slice_formats(stats, 0.05,
                               prev=np.array([1], dtype=np.uint8)), [1])
        # was dense: 4.5% is under the threshold but NOT under
        # threshold/band — stays dense
        d_under = int(n * 65536 * 0.045)
        stats2 = self._stats([(n, d_under, 4000)])
        np.testing.assert_array_equal(
            pick_slice_formats(stats2, 0.05,
                               prev=np.array([0], dtype=np.uint8)), [0])
        # was dense, 3%: crosses threshold/band -> converts to sparse
        d_deep = int(n * 65536 * 0.03)
        stats3 = self._stats([(n, d_deep, 4000)])
        np.testing.assert_array_equal(
            pick_slice_formats(stats3, 0.05,
                               prev=np.array([0], dtype=np.uint8)), [1])
        # crossing the far band edge always converts to dense
        d_out = int(n * 65536 * 0.07)
        stats4 = self._stats([(n, d_out, 4000)])
        np.testing.assert_array_equal(
            pick_slice_formats(stats4, 0.05,
                               prev=np.array([1], dtype=np.uint8)), [0])


# -- 3. serving ---------------------------------------------------------------


class TestSparseServe:
    OPS = ("Intersect", "Union", "Difference")

    def test_sparse_and_mixed_counts_match_host(self, holder, monkeypatch):
        seed_sparse(holder, "sp")
        seed_dense(holder, "dn")
        poison_per_slice(monkeypatch)
        e = Executor(holder, use_device=True)
        host = Executor(holder, use_device=False)
        queries = ["Count(Bitmap(rowID=1, frame=sp))",
                   "Count(Bitmap(rowID=999, frame=sp))"]
        for op in self.OPS:
            queries.append(
                f"Count({op}(Bitmap(rowID=1, frame=sp), "
                "Bitmap(rowID=2, frame=sp)))")
            queries.append(
                f"Count({op}(Bitmap(rowID=1, frame=sp), "
                "Bitmap(rowID=2, frame=dn)))")
            queries.append(
                f"Count({op}(Bitmap(rowID=1, frame=dn), "
                "Bitmap(rowID=2, frame=sp)))")
        for pql in queries:
            assert q(e, "i", pql) == q(host, "i", pql), pql
        mgr = e.mesh_manager()
        assert mgr.stats["sparse_count"] > 0
        assert mgr.stats["stage_sparse_slices"] > 0
        sv = mgr._views.get(("i", "sp", "standard"))
        assert sv is not None and sv.sparse is not None
        assert sv.slice_formats.any()

    def test_incremental_write_restages_exactly(self, holder, monkeypatch):
        f = seed_sparse(holder, "sp")
        poison_per_slice(monkeypatch)
        e = Executor(holder, use_device=True)
        host = Executor(holder, use_device=False)
        pql = "Count(Bitmap(rowID=1, frame=sp))"
        assert q(e, "i", pql) == q(host, "i", pql)
        f.set_bit(1, 123456)
        assert q(e, "i", pql) == q(host, "i", pql)
        assert e.mesh_manager().stats.get("refresh_pick_restage", 0) >= 1

    def test_demote_on_nary_tree_stays_on_device(self, holder,
                                                 monkeypatch):
        seed_sparse(holder, "sp", rows=(1, 2, 3))
        poison_per_slice(monkeypatch)
        e = Executor(holder, use_device=True)
        host = Executor(holder, use_device=False)
        pair = ("Count(Intersect(Bitmap(rowID=1, frame=sp), "
                "Bitmap(rowID=2, frame=sp)))")
        assert q(e, "i", pair) == q(host, "i", pair)
        mgr = e.mesh_manager()
        assert mgr._views[("i", "sp", "standard")].sparse is not None
        # 3-leaf union: only the packed-word fold serves it — the view
        # demotes to dense and the DEVICE answers (host is poisoned)
        tri = ("Count(Union(Bitmap(rowID=1, frame=sp), "
               "Bitmap(rowID=2, frame=sp), Bitmap(rowID=3, frame=sp)))")
        assert q(e, "i", tri) == q(host, "i", tri)
        assert mgr.stats["sparse_demote"] >= 1
        sv = mgr._views[("i", "sp", "standard")]
        assert sv.sparse is None
        # pin is sticky: a pair query keeps serving dense, no flap back
        assert q(e, "i", pair) == q(host, "i", pair)
        assert mgr._views[("i", "sp", "standard")].sparse is None
        # invalidate clears the pin: the view may stage sparse again
        # (ask a fresh pair so no memo can answer without staging)
        mgr.invalidate()
        pair23 = ("Count(Intersect(Bitmap(rowID=2, frame=sp), "
                  "Bitmap(rowID=3, frame=sp)))")
        assert q(e, "i", pair23) == q(host, "i", pair23)
        assert mgr._views[("i", "sp", "standard")].sparse is not None

    def test_threshold_env_kill_switch(self, holder, monkeypatch):
        seed_sparse(holder, "sp")
        monkeypatch.setenv("PILOSA_TPU_SPARSE_DENSITY_THRESHOLD", "0")
        e = Executor(holder, use_device=True)
        host = Executor(holder, use_device=False)
        pql = "Count(Bitmap(rowID=1, frame=sp))"
        assert q(e, "i", pql) == q(host, "i", pql)
        mgr = e.mesh_manager()
        sv = mgr._views[("i", "sp", "standard")]
        assert sv.sparse is None
        assert mgr._sparse_views == 0

    def test_residency_gauge(self, holder):
        seed_sparse(holder, "sp")
        e = Executor(holder, use_device=True)
        q(e, "i", "Count(Bitmap(rowID=1, frame=sp))")
        dm = e.mesh_manager().device_memory()
        assert dm["sparse_bytes"] > 0
        assert 0 < dm["residency_ratio"] <= 1.0
        assert dm["per_device"]
        assert set(dm["residency_per_device"]) == set(dm["per_device"])
        for r in dm["residency_per_device"].values():
            assert 0 <= r <= 1.0

    def test_explain_reports_format(self, holder):
        seed_sparse(holder, "sp")
        e = Executor(holder, use_device=True)
        pql = ("Count(Intersect(Bitmap(rowID=1, frame=sp), "
               "Bitmap(rowID=2, frame=sp)))")
        plan = e.explain("i", parse_string(pql))
        call = plan["calls"][0]
        # pre-stage: the staging estimate prices the sparse pick
        view = call["staging"]["views"][0]
        assert view["format"] == "sparse"
        assert call["staging"]["estimated_h2d_bytes"] > 0
        q(e, "i", pql)
        call2 = e.explain("i", parse_string(pql))["calls"][0]
        assert call2["staging"]["views"][0]["resident"] is True
        assert call2["staging"]["views"][0]["format"] == "sparse"
        assert call2["device_format"]["leaves"] == ["sparse", "sparse"]
        assert call2["device_format"]["sparse_shape"] == "and"


class TestMixedEviction:
    def test_mixed_format_eviction_under_budget(self, tmp_path,
                                                monkeypatch):
        """Round-robin over sparse + dense frames under a budget that
        can't hold the whole working set: answers stay exact, the
        governor's byte ledger tracks ACTUAL (sparse) bytes, and the
        staged total respects the budget."""
        h = Holder(str(tmp_path / "data"))
        h.open()
        try:
            frames = ["sp1", "sp2", "dn1", "dn2"]
            seed_sparse(h, "sp1", slices=1, seed=3)
            seed_sparse(h, "sp2", slices=1, seed=4)
            seed_dense(h, "dn1", slices=1, seed=5)
            seed_dense(h, "dn2", slices=1, seed=6)
            probe = Executor(h, use_device=True,
                             mesh_config={"hbm_budget_bytes": -1})
            host = Executor(h, use_device=False)
            for fr in frames:
                assert q(probe, "i", f"Count(Bitmap(rowID=1, frame={fr}))") \
                    == q(host, "i", f"Count(Bitmap(rowID=1, frame={fr}))")
            mgr = probe.mesh_manager()
            per_view = {k[1]: mgr._view_bytes(v)
                        for k, v in mgr._views.items()}
            # the ledger charges sparse pools their actual (small) bytes
            assert per_view["sp1"] < per_view["dn1"]
            total = sum(per_view.values())
            budget = int(total - per_view["dn1"] // 2)  # can't hold all
            e = Executor(h, use_device=True,
                         mesh_config={"hbm_budget_bytes": budget})
            for i in range(12):
                fr = frames[i % len(frames)]
                pql = f"Count(Bitmap(rowID=1, frame={fr}))"
                assert q(e, "i", pql) == q(host, "i", pql), pql
            smgr = e.mesh_manager()
            assert smgr.stats["evicted_budget"] > 0
            assert smgr.stats["staged_bytes"] <= budget
            # a sparse view survived or restaged — and the gauge is live
            dm = smgr.device_memory()
            assert dm["padded_bytes"] <= budget
            assert 0 < dm["residency_ratio"] <= 1.0
        finally:
            h.close()
