"""Executor tests (model: /root/reference/executor_test.go — real local
executor, mocked remote client at the RPC seam)."""

from datetime import datetime

import pytest

from pilosa_tpu.core import Holder
from pilosa_tpu.errors import QueryError
from pilosa_tpu.executor import ExecOptions, Executor
from pilosa_tpu.parallel import Cluster, ModHasher, Node
from pilosa_tpu.pql import parse_string
from pilosa_tpu import SLICE_WIDTH


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


def make_executor(holder, **kw):
    return Executor(holder, use_device=kw.pop("use_device", False), **kw)


def seed(holder, index="i", frame="general", bits=()):
    idx = holder.create_index_if_not_exists(index)
    f = idx.create_frame_if_not_exists(frame)
    for row, col in bits:
        f.set_bit(row, col)
    return f


def q(executor, index, pql, slices=None, opt=None):
    return executor.execute(index, parse_string(pql), slices, opt)


class TestBitmapCalls:
    def test_bitmap(self, holder):
        seed(holder, bits=[(10, 0), (10, 3), (10, SLICE_WIDTH + 1)])
        e = make_executor(holder)
        row = q(e, "i", "Bitmap(rowID=10)")[0]
        assert list(row) == [0, 3, SLICE_WIDTH + 1]

    def test_bitmap_attaches_row_attrs(self, holder):
        f = seed(holder, bits=[(10, 0)])
        f.row_attr_store.set_attrs(10, {"foo": "bar"})
        e = make_executor(holder)
        row = q(e, "i", "Bitmap(rowID=10)")[0]
        assert row.attrs == {"foo": "bar"}

    def test_intersect_union_difference(self, holder):
        seed(holder, bits=[
            (10, 0), (10, 1), (10, SLICE_WIDTH + 2),
            (11, 1), (11, 2), (11, SLICE_WIDTH + 2),
        ])
        e = make_executor(holder)
        assert list(q(e, "i", "Intersect(Bitmap(rowID=10), Bitmap(rowID=11))")[0]) \
            == [1, SLICE_WIDTH + 2]
        assert list(q(e, "i", "Union(Bitmap(rowID=10), Bitmap(rowID=11))")[0]) \
            == [0, 1, 2, SLICE_WIDTH + 2]
        assert list(q(e, "i", "Difference(Bitmap(rowID=10), Bitmap(rowID=11))")[0]) \
            == [0]

    def test_count(self, holder):
        seed(holder, bits=[(10, 3), (10, SLICE_WIDTH + 1), (10, 2 * SLICE_WIDTH + 5)])
        e = make_executor(holder)
        assert q(e, "i", "Count(Bitmap(rowID=10))")[0] == 3

    def test_count_device_matches_host(self, holder):
        seed(holder, bits=[
            (10, 0), (10, 1), (10, SLICE_WIDTH + 2), (10, 65536 + 7),
            (11, 1), (11, SLICE_WIDTH + 2), (11, 99999),
        ])
        host = make_executor(holder, use_device=False)
        dev = make_executor(holder, use_device=True)
        for pql in (
            "Count(Bitmap(rowID=10))",
            "Count(Intersect(Bitmap(rowID=10), Bitmap(rowID=11)))",
            "Count(Union(Bitmap(rowID=10), Bitmap(rowID=11)))",
            "Count(Difference(Bitmap(rowID=10), Bitmap(rowID=11)))",
            "Count(Bitmap(rowID=999))",
        ):
            assert q(dev, "i", pql)[0] == q(host, "i", pql)[0], pql

    def test_range(self, holder):
        idx = holder.create_index_if_not_exists("i")
        f = idx.create_frame_if_not_exists("general", time_quantum="YMDH")
        f.set_bit(1, 100, t=datetime(2017, 4, 2, 12, 0))
        f.set_bit(1, 200, t=datetime(2017, 4, 3, 9, 0))
        f.set_bit(1, 300, t=datetime(2018, 1, 1, 0, 0))
        e = make_executor(holder)
        row = q(e, "i", 'Range(rowID=1, frame="general", start="2017-04-01T00:00", end="2017-05-01T00:00")')[0]
        assert list(row) == [100, 200]

    def test_count_empty_query_error(self, holder):
        seed(holder)
        e = make_executor(holder)
        with pytest.raises(QueryError):
            q(e, "i", "Count()")


class TestTopN:
    def test_topn(self, holder):
        bits = [(0, c) for c in range(5)] + [(1, c) for c in range(3)] \
            + [(2, c) for c in range(8)] + [(3, SLICE_WIDTH + 1)]
        seed(holder, bits=bits)
        e = make_executor(holder)
        pairs = q(e, "i", 'TopN(frame="general", n=2)')[0]
        assert pairs == [(2, 8), (0, 5)]

    def test_topn_with_src(self, holder):
        bits = [(0, c) for c in range(5)] + [(1, c) for c in range(10, 13)] \
            + [(2, c) for c in range(8)] + [(9, 0), (9, 1), (9, 11)]
        seed(holder, bits=bits)
        e = make_executor(holder)
        pairs = q(e, "i", 'TopN(Bitmap(rowID=9), frame="general", n=3)')[0]
        # Intersection counts with row 9 {0,1,11}: row9->3, row0->2, row2->2.
        assert pairs == [(9, 3), (0, 2), (2, 2)]

    def test_topn_multislice_exact_recount(self, holder):
        # Row 0 dominates slice 0, row 1 dominates slice 1; exact phase-2
        # recount must rank globally.
        bits = [(0, c) for c in range(10)] + [(1, c) for c in range(4)] \
            + [(1, SLICE_WIDTH + c) for c in range(9)]
        seed(holder, bits=bits)
        e = make_executor(holder)
        pairs = q(e, "i", 'TopN(frame="general", n=2)')[0]
        assert pairs == [(1, 13), (0, 10)]


class TestWrites:
    def test_setbit_clearbit(self, holder):
        seed(holder)
        e = make_executor(holder)
        assert q(e, "i", "SetBit(frame=\"general\", rowID=1, columnID=9)")[0] is True
        assert q(e, "i", "SetBit(frame=\"general\", rowID=1, columnID=9)")[0] is False
        assert list(q(e, "i", "Bitmap(rowID=1)")[0]) == [9]
        assert q(e, "i", "ClearBit(frame=\"general\", rowID=1, columnID=9)")[0] is True
        assert q(e, "i", "ClearBit(frame=\"general\", rowID=1, columnID=9)")[0] is False

    def test_setbit_with_timestamp(self, holder):
        idx = holder.create_index_if_not_exists("i")
        idx.create_frame_if_not_exists("general", time_quantum="YM")
        e = make_executor(holder)
        q(e, "i", 'SetBit(frame="general", rowID=1, columnID=2, timestamp="2017-04-02T12:30")')
        row = q(e, "i", 'Range(rowID=1, frame="general", start="2017-04-01T00:00", end="2017-05-01T00:00")')[0]
        assert list(row) == [2]

    def test_set_row_attrs(self, holder):
        f = seed(holder)
        e = make_executor(holder)
        q(e, "i", 'SetRowAttrs(frame="general", rowID=7, x=123, y="z", b=true)')
        assert f.row_attr_store.attrs(7) == {"x": 123, "y": "z", "b": True}
        # Bulk fast path: multiple SetRowAttrs in one query.
        res = q(e, "i", 'SetRowAttrs(frame="general", rowID=8, v=1)\n'
                        'SetRowAttrs(frame="general", rowID=9, v=2)')
        assert res == [None, None]
        assert f.row_attr_store.attrs(8) == {"v": 1}
        assert f.row_attr_store.attrs(9) == {"v": 2}

    def test_set_column_attrs(self, holder):
        seed(holder)
        e = make_executor(holder)
        q(e, "i", 'SetColumnAttrs(id=3, color="red")')
        assert holder.index("i").column_attr_store.attrs(3) == {"color": "red"}


class TestDistributed:
    """Real local executor + mocked remote (executor_test.go:473-693)."""

    def _cluster(self, replica_n=1):
        return Cluster(nodes=[Node("host0"), Node("host1")],
                       hasher=ModHasher(), partition_n=4, replica_n=replica_n)

    def test_remote_count_forwarded(self, holder):
        seed(holder, bits=[(10, s * SLICE_WIDTH) for s in range(4)])
        cluster = self._cluster()
        calls = []

        class MockClient:
            def execute_query(self, node, index, query, slices, remote):
                calls.append((node.host, index, query, tuple(slices), remote))
                return [len(slices)]  # 1 bit per slice seeded above

        e = Executor(holder, host="host0", cluster=cluster,
                     client=MockClient(), use_device=False)
        total = q(e, "i", "Count(Bitmap(rowID=10))")[0]
        assert total == 4
        # Exactly the slices host1 owns were forwarded, query re-serialized.
        (host, index, query, slices, remote), = calls
        assert host == "host1" and index == "i" and remote is True
        assert query == "Count(Bitmap(rowID=10))"
        expected = tuple(s for s in range(4)
                         if cluster.fragment_nodes("i", s)[0].host == "host1")
        assert slices == expected and len(slices) > 0

    def test_remote_failure_fails_over_to_replica(self, holder):
        seed(holder, bits=[(10, s * SLICE_WIDTH) for s in range(4)])
        cluster = self._cluster(replica_n=2)

        class FailingClient:
            def execute_query(self, node, index, query, slices, remote):
                raise ConnectionError("node down")

        e = Executor(holder, host="host0", cluster=cluster,
                     client=FailingClient(), use_device=False)
        # host1's slices re-split onto host0 (the replica), served locally.
        assert q(e, "i", "Count(Bitmap(rowID=10))")[0] == 4

    def test_remote_failure_no_replica_raises(self, holder):
        seed(holder, bits=[(10, s * SLICE_WIDTH) for s in range(4)])
        cluster = self._cluster(replica_n=1)

        class FailingClient:
            def execute_query(self, node, index, query, slices, remote):
                raise ConnectionError("node down")

        e = Executor(holder, host="host0", cluster=cluster,
                     client=FailingClient(), use_device=False)
        with pytest.raises(ConnectionError):
            q(e, "i", "Count(Bitmap(rowID=10))")

    def test_remote_opt_restricts_to_local(self, holder):
        seed(holder, bits=[(10, s * SLICE_WIDTH) for s in range(4)])
        cluster = self._cluster()

        class ExplodingClient:
            def execute_query(self, *a, **kw):
                raise AssertionError("remote exec must not happen when opt.remote")

        e = Executor(holder, host="host0", cluster=cluster,
                     client=ExplodingClient(), use_device=False)
        local = [s for s in range(4)
                 if cluster.fragment_nodes("i", s)[0].host == "host0"]
        n = e.execute("i", parse_string("Count(Bitmap(rowID=10))"),
                      local, ExecOptions(remote=True))[0]
        assert n == len(local)

    def test_setbit_routed_to_replicas(self, holder):
        seed(holder)
        cluster = self._cluster(replica_n=2)
        calls = []

        class MockClient:
            def execute_query(self, node, index, query, slices, remote):
                calls.append((node.host, query))
                return [True]

        e = Executor(holder, host="host0", cluster=cluster,
                     client=MockClient(), use_device=False)
        changed = q(e, "i", 'SetBit(frame="general", rowID=1, columnID=0)')[0]
        assert changed is True
        # Local write applied + forwarded to the other replica once.
        assert list(holder.fragment("i", "general", "standard", 0).row(1)) == [0]
        assert calls == [("host1", 'SetBit(columnID=0, frame="general", rowID=1)')]


class TestDeviceTopN:
    def test_topn_device_matches_host(self, holder):
        """Plain TopN takes the exact device path (pool_row_counts);
        results must match the host rank-cache path, including
        thresholds and ties."""
        bits = []
        for r, k in [(1, 7), (2, 12), (3, 3), (9, 12)]:
            bits += [(r, c * 131) for c in range(k)]
        bits += [(5, SLICE_WIDTH + 1), (5, SLICE_WIDTH + 2)]
        seed(holder, bits=bits)
        host = make_executor(holder, use_device=False)
        dev = make_executor(holder, use_device=True)
        for pql in (
            "TopN(frame=general, n=3)",
            "TopN(frame=general, n=100)",
            "TopN(frame=general, n=2, threshold=4)",
        ):
            assert q(dev, "i", pql)[0] == q(host, "i", pql)[0], pql

    def test_topn_filters_keep_host_path(self, holder):
        """Attr-filtered TopN needs the host attr store; the device gate
        must not hijack it."""
        seed(holder, bits=[(1, 0), (1, 5), (2, 7)])
        f = holder.frame("i", "general")
        f.row_attr_store.set_attrs(1, {"cat": "x"})
        f.row_attr_store.set_attrs(2, {"cat": "y"})
        dev = make_executor(holder, use_device=True)
        res = q(dev, "i", 'TopN(frame=general, n=5, field="cat",'
                          ' filters=["x"])')[0]
        assert res == [(1, 2)]


class TestDeviceTreeFuzz:
    def test_random_trees_device_matches_host(self, holder):
        """Randomized op-tree differential: Count over random
        Intersect/Union/Difference trees, fused device plan vs host
        roaring (the executor-level analog of the kernel differential
        suite)."""
        import random

        rng = random.Random(4242)
        rows = list(range(1, 9))
        bits = []
        for r in rows:
            k = rng.randrange(0, 200)
            cols = rng.sample(range(2 * SLICE_WIDTH), k=k)
            bits += [(r, c) for c in cols]
        bits.append((1, 0))  # rows 1 always exists
        seed(holder, bits=bits)
        host = make_executor(holder, use_device=False)
        dev = make_executor(holder, use_device=True)

        def gen_tree(depth):
            if depth == 0 or rng.random() < 0.4:
                return f"Bitmap(rowID={rng.choice(rows + [777])})"
            op = rng.choice(["Intersect", "Union", "Difference"])
            n = rng.randrange(2, 4)
            children = ", ".join(gen_tree(depth - 1) for _ in range(n))
            return f"{op}({children})"

        for _ in range(40):
            pql = f"Count({gen_tree(rng.randrange(1, 4))})"
            a = q(dev, "i", pql)[0]
            b = q(host, "i", pql)[0]
            assert a == b, (pql, a, b)


class TestDeviceRange:
    def test_count_range_device_matches_host(self, holder):
        """Count(Range(...)) lowers to an OR over time-view leaves on
        device; absent view fragments contribute empty, matching the
        host union path."""
        idx = holder.create_index_if_not_exists("i")
        f = idx.create_frame_if_not_exists("general", time_quantum="YMD")
        f.set_bit(1, 100, t=datetime(2017, 4, 2, 12, 0))
        f.set_bit(1, 200, t=datetime(2017, 4, 28, 9, 0))
        f.set_bit(1, 100, t=datetime(2017, 5, 2, 1, 0))   # dup col, later
        f.set_bit(1, 300, t=datetime(2018, 1, 1, 0, 0))   # outside range
        f.set_bit(2, 400, t=datetime(2017, 4, 3, 0, 0))   # other row
        host = make_executor(holder, use_device=False)
        dev = make_executor(holder, use_device=True)
        for pql in (
            'Count(Range(rowID=1, frame="general",'
            ' start="2017-04-01T00:00", end="2017-05-01T00:00"))',
            'Count(Range(rowID=1, frame="general",'
            ' start="2017-04-01T00:00", end="2017-06-01T00:00"))',
            'Count(Union(Range(rowID=1, frame="general",'
            ' start="2017-04-01T00:00", end="2017-05-01T00:00"),'
            ' Bitmap(rowID=2, frame="general")))',
            'Count(Range(rowID=9, frame="general",'
            ' start="2017-04-01T00:00", end="2017-05-01T00:00"))',
            'Count(Range(rowID=1, frame="general",'
            ' start="2019-01-01T00:00", end="2019-02-01T00:00"))',
        ):
            a = q(dev, "i", pql)[0]
            b = q(host, "i", pql)[0]
            assert a == b, (pql, a, b)
        # sanity: the first range really finds 2 columns
        assert q(host, "i",
                 'Count(Range(rowID=1, frame="general",'
                 ' start="2017-04-01T00:00", end="2017-05-01T00:00"))')[0] == 2


class TestHostQueryCache:
    """Generation-validated caches on the cost-routed host count path
    (VERDICT r3 #4): repeats serve from the memo, writes invalidate."""

    def _routed(self, holder):
        # device backend "on" but every query under the work threshold
        # routes to the host plan — the small-query serving path.
        seed(holder, bits=[(r, c) for r in range(3) for c in (1, 2, 70000)])
        return Executor(holder, use_device=True, device_min_work=10**9)

    def test_repeat_hits_memo_and_blocks(self, holder):
        e = self._routed(holder)
        pql = "Count(Intersect(Bitmap(rowID=0), Bitmap(rowID=1)))"
        assert q(e, "i", pql)[0] == 3
        h0 = dict(e.host_cache_stats)
        # an immediate repeat is answered by the query-level memo (one
        # epoch compare), never reaching the per-slice layer
        assert q(e, "i", pql)[0] == 3
        assert e.host_cache_stats["query_hit"] > h0["query_hit"]
        assert e.host_cache_stats["memo_hit"] == h0["memo_hit"]
        # an UNRELATED write moves the global epoch (query memo misses)
        # but not this query's fragment generations — the per-slice
        # memo layer answers those slices without refolding
        seed(holder, index="other", bits=[(0, 1)])
        h1 = dict(e.host_cache_stats)
        assert q(e, "i", pql)[0] == 3
        assert e.host_cache_stats["memo_hit"] > h1["memo_hit"]

    def test_write_invalidates(self, holder):
        e = self._routed(holder)
        pql = "Count(Intersect(Bitmap(rowID=0), Bitmap(rowID=1)))"
        assert q(e, "i", pql)[0] == 3
        assert q(e, "i", pql)[0] == 3  # memoized
        holder.frame("i", "general").clear_bit(0, 2)
        assert q(e, "i", pql)[0] == 2  # generation bumped -> recompute
        holder.frame("i", "general").set_bit(0, 2)
        assert q(e, "i", pql)[0] == 3

    def test_fragment_recreation_invalidates(self, holder):
        e = self._routed(holder)
        pql = "Count(Bitmap(rowID=0))"
        assert q(e, "i", pql)[0] == 3
        holder.delete_index("i")
        seed(holder, bits=[(0, 5)])
        # new Fragment OBJECT: identity check fails, memo recomputes
        assert q(e, "i", pql)[0] == 1

    def test_different_rows_are_distinct_keys(self, holder):
        e = self._routed(holder)
        assert q(e, "i", "Count(Bitmap(rowID=0))")[0] == 3
        assert q(e, "i", "Count(Bitmap(rowID=1))")[0] == 3
        f = holder.frame("i", "general")
        f.set_bit(1, 9)
        assert q(e, "i", "Count(Bitmap(rowID=1))")[0] == 4
        assert q(e, "i", "Count(Bitmap(rowID=0))")[0] == 3

    def test_bounds(self):
        from pilosa_tpu.parallel.plan import HostQueryCache

        c = HostQueryCache()
        class F:  # stand-in fragment
            pass
        frags = [F() for _ in range(c._BLOCKS_MAX + 10)]
        for i, fr in enumerate(frags):
            c.block_put(fr, 0, 1, i)
        assert len(c._blocks) == c._BLOCKS_MAX
        # oldest evicted, newest present
        assert c.block_get(frags[-1], 0, 1) == len(frags) - 1
        assert c.block_get(frags[0], 0, 1) is None
        for i in range(c._MEMO_MAX + 10):
            c.memo_put(("i", "s", ("l",), i), ((None, -1),), i)
        assert len(c._memo) == c._MEMO_MAX

    def test_deleted_fragments_not_pinned(self, holder):
        import gc
        import weakref

        e = self._routed(holder)
        pql = "Count(Bitmap(rowID=0))"
        assert q(e, "i", pql)[0] == 3
        frag = holder.fragment("i", "general", "standard", 0)
        wr = weakref.ref(frag)
        del frag
        holder.delete_index("i")
        gc.collect()
        # cache entries hold weak refs only — the deleted index's
        # fragment (and its parsed storage) must be collectable
        assert wr() is None


class TestQueryLevelMemo:
    """Whole-query Count memo validated by the process-wide mutation
    epoch (VERDICT r4 #4): a repeated read-only Count is one dict probe,
    and EVERY mutation class — bits, schema, labels, quanta — bumps the
    epoch so a hit can never be stale."""

    def _exec(self, holder):
        seed(holder, bits=[(r, c) for r in range(3) for c in (1, 2, 70000)])
        return Executor(holder, use_device=True, device_min_work=10**9)

    def test_repeat_hits_query_memo_across_reparse(self, holder):
        e = self._exec(holder)
        pql = "Count(Union(Bitmap(rowID=0), Bitmap(rowID=1)))"
        assert q(e, "i", pql)[0] == 3  # rows share columns {1,2,70000}
        h0 = e.host_cache_stats["query_hit"]
        # a RE-PARSED query (fresh Call objects) still hits: the key is
        # structural, not object identity
        assert q(e, "i", pql)[0] == 3
        assert e.host_cache_stats["query_hit"] == h0 + 1

    def test_every_mutation_class_bumps_epoch(self, holder):
        from pilosa_tpu.core.fragment import MUTATION_EPOCH
        from pilosa_tpu.core.timequantum import TimeQuantum

        e = self._exec(holder)
        f = holder.frame("i", "general")
        idx = holder.index("i")

        def bumped(fn):
            n0 = MUTATION_EPOCH.n
            fn()
            return MUTATION_EPOCH.n > n0

        assert bumped(lambda: f.set_bit(9, 9))
        assert bumped(lambda: f.clear_bit(9, 9))
        assert bumped(lambda: f.import_bits([5], [123]))
        assert bumped(lambda: f.set_time_quantum(TimeQuantum("YMD")))
        assert bumped(lambda: f.set_row_label("rid"))
        assert bumped(lambda: idx.set_time_quantum(TimeQuantum("YM")))
        assert bumped(lambda: idx.set_column_label("cid"))
        assert bumped(lambda: idx.create_frame("other"))
        assert bumped(lambda: idx.delete_frame("other"))
        assert bumped(lambda: holder.create_index("j"))
        assert bumped(lambda: holder.delete_index("j"))
        # a no-op write also bumps (it still appends to the mutation
        # log) — over-invalidation is the safe direction

    def test_write_between_repeats_recomputes(self, holder):
        e = self._exec(holder)
        pql = "Count(Bitmap(rowID=0))"
        assert q(e, "i", pql)[0] == 3
        assert q(e, "i", pql)[0] == 3
        holder.frame("i", "general").set_bit(0, 555)
        assert q(e, "i", pql)[0] == 4

    def test_cluster_mode_never_query_memoizes(self, holder):
        seed(holder, bits=[(0, 1)])
        nodes = [Node("h1:1"), Node("h2:1")]
        cluster = Cluster(nodes=nodes, hasher=ModHasher())
        e = Executor(holder, host="h1:1", cluster=cluster, use_device=False)
        # remote fan-out would fail (no client); local-slices remote
        # form exercises the path without one
        q(e, "i", "Count(Bitmap(rowID=0))", slices=[0],
          opt=ExecOptions(remote=True))
        assert e.host_cache_stats["query_hit"] == 0
        assert e.host_cache_stats["query_miss"] == 0

    def test_explicit_slices_are_distinct_keys(self, holder):
        e = self._exec(holder)
        f = holder.frame("i", "general")
        f.set_bit(7, SLICE_WIDTH + 3)  # slice 1
        f.set_bit(7, 3)                # slice 0
        assert q(e, "i", "Count(Bitmap(rowID=7))", slices=[0])[0] == 1
        assert q(e, "i", "Count(Bitmap(rowID=7))")[0] == 2
        assert q(e, "i", "Count(Bitmap(rowID=7))", slices=[1])[0] == 1


class TestQueryMemoRevalidation:
    """r5 second tier: entries carry (structural epoch, fragment
    generations); an epoch bump from an UNRELATED write revalidates in
    a generation walk instead of refolding, while touched-fragment
    writes and any structural change (new fragment/frame/index, label
    or quantum change) still invalidate."""

    def _exec(self, holder):
        seed(holder, bits=[(r, c) for r in range(3) for c in (1, 2, 70000)])
        # a second frame that exists BEFORE the memo is stored, so
        # writing to it later is a plain bit write, not a create
        holder.index("i").create_frame_if_not_exists("other")
        holder.frame("i", "other").set_bit(0, 1)
        return Executor(holder, use_device=True, device_min_work=10**9)

    def test_unrelated_write_revalidates(self, holder):
        e = self._exec(holder)
        pql = "Count(Bitmap(rowID=0))"
        assert q(e, "i", pql)[0] == 3
        r0 = e.host_cache_stats["query_reval"]
        m0 = e.host_cache_stats["query_miss"]
        holder.frame("i", "other").set_bit(5, 99)  # bumps epoch only
        assert q(e, "i", pql)[0] == 3
        assert e.host_cache_stats["query_reval"] == r0 + 1
        assert e.host_cache_stats["query_miss"] == m0

    def test_revalidated_entry_restamps(self, holder):
        # after one revalidation, an unmutated repeat takes the fast
        # epoch path again (the entry was re-stamped)
        e = self._exec(holder)
        pql = "Count(Bitmap(rowID=0))"
        assert q(e, "i", pql)[0] == 3
        holder.frame("i", "other").set_bit(5, 99)
        assert q(e, "i", pql)[0] == 3
        h0 = e.host_cache_stats["query_hit"]
        assert q(e, "i", pql)[0] == 3
        assert e.host_cache_stats["query_hit"] == h0 + 1

    def test_touched_write_refolds(self, holder):
        e = self._exec(holder)
        pql = "Count(Bitmap(rowID=0))"
        assert q(e, "i", pql)[0] == 3
        r0 = e.host_cache_stats["query_reval"]
        holder.frame("i", "general").set_bit(0, 555)
        assert q(e, "i", pql)[0] == 4
        assert e.host_cache_stats["query_reval"] == r0

    def test_noop_touched_write_refolds_same_count(self, holder):
        # re-setting a set bit bumps the generation (logged) — the
        # memo can't know it was a no-op, so it refolds, correctly
        e = self._exec(holder)
        pql = "Count(Bitmap(rowID=0))"
        assert q(e, "i", pql)[0] == 3
        r0 = e.host_cache_stats["query_reval"]
        m0 = e.host_cache_stats["query_miss"]
        holder.frame("i", "general").set_bit(0, 1)  # already set
        assert q(e, "i", pql)[0] == 3
        assert e.host_cache_stats["query_reval"] == r0
        assert e.host_cache_stats["query_miss"] == m0 + 1

    def test_structural_change_invalidates(self, holder):
        e = self._exec(holder)
        pql = "Count(Bitmap(rowID=0))"
        assert q(e, "i", pql)[0] == 3
        m0 = e.host_cache_stats["query_miss"]
        holder.create_index("scratch")  # structural: token must die
        assert q(e, "i", pql)[0] == 3
        assert e.host_cache_stats["query_miss"] == m0 + 1

    def test_new_fragment_in_queried_slices_recounts(self, holder):
        e = self._exec(holder)
        pql = "Count(Bitmap(rowID=0))"
        # slice 1 has no fragment yet; memo over slices [0, 1]
        assert q(e, "i", pql, slices=[0, 1])[0] == 3
        holder.frame("i", "general").set_bit(0, SLICE_WIDTH + 8)
        assert q(e, "i", pql, slices=[0, 1])[0] == 4


class TestCallCacheKey:
    def test_structural_equality_across_parses(self):
        a = parse_string("Count(Intersect(Bitmap(rowID=1), Bitmap(rowID=2)))")
        b = parse_string("Count(Intersect(Bitmap(rowID=1), Bitmap(rowID=2)))")
        assert a.calls[0].cache_key() == b.calls[0].cache_key()
        c = parse_string("Count(Intersect(Bitmap(rowID=1), Bitmap(rowID=3)))")
        assert a.calls[0].cache_key() != c.calls[0].cache_key()

    def test_list_args_hash(self):
        a = parse_string("TopN(frame=f, n=2, ids=[1,2,3])").calls[0]
        b = parse_string("TopN(frame=f, n=2, ids=[1,2,3])").calls[0]
        assert a.cache_key() == b.cache_key() is not None
        hash(a.cache_key())

    def test_clone_does_not_copy_memo(self):
        a = parse_string("TopN(frame=f, n=2)").calls[0]
        k0 = a.cache_key()
        cl = a.clone()
        cl.args["ids"] = [9, 8]
        assert cl.cache_key() != k0
        assert a.cache_key() == k0


class TestFusedMaterialize:
    """Bitmap-ROOTED (non-Count) trees run the fused dense-fold path
    (VERDICT r4 #5): result equality against the per-slice roaring
    merge it replaced, form-correct containers, and write
    invalidation through the epoch-validated matrix cache."""

    def test_random_trees_match_roaring_path(self, holder):
        import random

        from pilosa_tpu.core.row import Row

        rng = random.Random(777)
        rows = list(range(1, 7))
        bits = [(1, 0)]
        for r in rows:
            cols = rng.sample(range(3 * SLICE_WIDTH),
                              k=rng.randrange(0, 300))
            bits += [(r, c) for c in cols]
        seed(holder, bits=bits)
        e = make_executor(holder, use_device=False)
        n_slices = holder.index("i").max_slice() + 1

        def gen_tree(depth):
            if depth == 0:
                return f"Bitmap(rowID={rng.choice(rows + [99])})"
            op = rng.choice(["Intersect", "Union", "Difference"])
            n = rng.randrange(2, 4)
            kids = ", ".join(
                gen_tree(depth - 1 if rng.random() < 0.5 else 0)
                for _ in range(n))
            return f"{op}({kids})"

        for _ in range(30):
            pql = gen_tree(rng.randrange(1, 3))
            got = q(e, "i", pql)[0]
            call = parse_string(pql).calls[0]
            want = Row()
            for s in range(n_slices):
                want.merge(e.execute_bitmap_call_slice("i", call, s))
            assert got.count() == want.count(), pql
            import numpy as np

            assert np.array_equal(got.columns(), want.columns()), pql

    def test_sparse_result_containers_are_array_form(self, holder):
        f = seed(holder, bits=[(1, c) for c in range(100)]
                 + [(2, c) for c in range(50, 70)])
        del f
        e = make_executor(holder, use_device=False)
        row = q(e, "i", "Intersect(Bitmap(rowID=1), Bitmap(rowID=2))")[0]
        assert row.count() == 20
        seg = row.segments[0]
        assert all(c.is_array() for c in seg.containers)
        # and the result is mutable without corrupting cached matrices
        row.set_bit(999)
        assert row.count() == 21

    def test_dense_result_containers_are_bitmap_form(self, holder):
        f = seed(holder, bits=[])
        f.import_bits([1] * 60000 + [2] * 60000,
                      list(range(60000)) + list(range(60000)))
        e = make_executor(holder, use_device=False)
        row = q(e, "i", "Intersect(Bitmap(rowID=1), Bitmap(rowID=2))")[0]
        assert row.count() == 60000
        assert any(not c.is_array() for c in row.segments[0].containers)

    def test_write_invalidates_fused_result(self, holder):
        f = seed(holder, bits=[(1, 5), (2, 5), (1, SLICE_WIDTH + 9),
                               (2, SLICE_WIDTH + 9)])
        e = make_executor(holder, use_device=False)
        pql = "Intersect(Bitmap(rowID=1), Bitmap(rowID=2))"
        assert q(e, "i", pql)[0].count() == 2
        assert q(e, "i", pql)[0].count() == 2  # matrices now cached
        f.set_bit(1, 777)
        f.set_bit(2, 777)
        assert q(e, "i", pql)[0].count() == 3

    def test_range_materializes_fused(self, holder):
        from datetime import datetime

        from pilosa_tpu.core.timequantum import TimeQuantum

        idx = holder.create_index_if_not_exists("i")
        f = idx.create_frame_if_not_exists("general",
                                           time_quantum=TimeQuantum("YMD"))
        f.set_bit(1, 3, datetime(2017, 1, 2))
        f.set_bit(1, 9, datetime(2017, 1, 3))
        f.set_bit(1, SLICE_WIDTH + 4, datetime(2017, 1, 4))
        e = make_executor(holder, use_device=False)
        row = q(e, "i", "Range(rowID=1, frame=general, "
                "start='2017-01-02T00:00', end='2017-01-05T00:00')")[0]
        assert sorted(row) == [3, 9, SLICE_WIDTH + 4]


class TestCacheKeyTypeSafety:
    def test_float_row_id_raises_even_after_int_memoized(self, holder):
        """1 == 1.0 == True in Python, but Count(rowID=1.0) must raise
        (uint_arg) even when Count(rowID=1) was just memoized — the
        cache key carries value TYPES."""
        seed(holder, bits=[(1, 5), (1, 9)])
        e = Executor(holder, use_device=True, device_min_work=10**9)
        assert q(e, "i", "Count(Bitmap(rowID=1))")[0] == 2
        assert q(e, "i", "Count(Bitmap(rowID=1))")[0] == 2  # memoized
        from pilosa_tpu.pql import Query
        from pilosa_tpu.pql.ast import Call

        float_q = Query(calls=[Call(name="Count", children=[
            Call(name="Bitmap", args={"rowID": 1.0})])])
        with pytest.raises(TypeError):
            e.execute("i", float_q)
        bool_q = Query(calls=[Call(name="Count", children=[
            Call(name="Bitmap", args={"rowID": True})])])
        with pytest.raises(TypeError):
            e.execute("i", bool_q)

    def test_typed_keys_distinguish(self):
        from pilosa_tpu.pql.ast import Call

        a = Call(name="Bitmap", args={"rowID": 1})
        b = Call(name="Bitmap", args={"rowID": 1.0})
        c = Call(name="Bitmap", args={"rowID": True})
        keys = {a.cache_key(), b.cache_key(), c.cache_key()}
        assert len(keys) == 3


class TestMemoConcurrency:
    def test_concurrent_reads_writes_converge_exact(self, holder):
        """Racing readers (query memo + parse cache hot) against a
        writer: no exceptions, every observed count is sane (monotone
        under a set-only writer), and the final quiesced count is
        exact. The host-layer analog of the dryrun's fault-evict-race
        surface."""
        import threading

        seed(holder, bits=[(1, c) for c in range(8)])
        e = make_executor(holder)
        f = holder.frame("i", "general")
        errors = []
        stop = threading.Event()

        def writer():
            try:
                c = 100
                while not stop.is_set():
                    f.set_bit(1, c)
                    c += 1
            except Exception as err:  # noqa: BLE001 — a dying writer
                #                       must FAIL the test, not
                #                       silently quiesce the race
                errors.append(err)

        def reader():
            from pilosa_tpu.pql import parse_string_cached

            try:
                for _ in range(300):
                    q_ = parse_string_cached("Count(Bitmap(rowID=1))")
                    n = e.execute("i", q_)[0]
                    # The memo's contract is epoch-consistency, not
                    # real-time monotonicity (a delayed query_put can
                    # briefly re-serve an older epoch-valid count), so
                    # assert only sanity bounds per observation.
                    assert n >= 8, n
            except Exception as err:  # noqa: BLE001
                errors.append(err)

        wt = threading.Thread(target=writer)
        rs = [threading.Thread(target=reader) for _ in range(3)]
        wt.start()
        [r.start() for r in rs]
        [r.join() for r in rs]
        stop.set()
        wt.join()
        assert not errors, errors
        want = holder.fragment("i", "general", "standard", 0).row(1).count()
        assert want > 8  # the writer really made progress
        assert e.execute(
            "i", parse_string("Count(Bitmap(rowID=1))"))[0] == want
