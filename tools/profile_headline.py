"""Profile the 1B-column Intersect+Count headline into components.

VERDICT r2 item 1(a): split the measured ~2.79 ms/query into
dispatch / gather / popcount-psum / readback, on the real chip, and
measure candidate restructurings before committing to one:

  noop         trivial jitted program over the same inputs — the pure
               dispatch floor through this rig's TPU relay
  stream       popcount the WHOLE pool with no gather — the HBM
               streaming ceiling for this shape (reads 1x pool bytes)
  current      compile_serve_count exactly as the serving path runs it
  gather_only  the two leaf gathers + u32 sum, no popcount fold —
               isolates gather cost from combine cost
  nomask       current minus the ownership-mask multiply
  noshard      current but plain jit, no shard_map/psum (1-device only)
  slab         contiguous dynamic-slice per leaf instead of flat gather
               (valid when a row's containers are contiguous in the
               pool — the dense-row common case; host checks idx)
  slab_scan    slab variant folded over slices with lax.scan to bound
               materialized intermediates
  batch16      the batch-16 program (amortized dispatch reference)

Usage: python tools/profile_headline.py [--slices N] [--iters N]
Writes PROFILE_HEADLINE.json and prints a table.
"""

import argparse
import json
import time

import numpy as np


def build_pool(num_slices, num_rows=2, seed=7):
    rng = np.random.default_rng(seed)
    cap = num_rows * 16
    keys = np.tile(np.arange(cap, dtype=np.int32), (num_slices, 1))
    words = rng.integers(0, 2**32, size=(num_slices, cap, 2048),
                         dtype=np.uint32)
    return keys, words


def sustained(fn, iters):
    out = fn()
    np.asarray(out)
    t0 = time.perf_counter()
    acc = None
    for _ in range(iters):
        o = fn()
        acc = o if acc is None else acc + o
    np.asarray(acc)
    return (time.perf_counter() - t0) / iters


def percall(fn, iters):
    import jax

    np.asarray(fn())
    t0 = time.perf_counter()
    for _ in range(iters):
        jax.block_until_ready(fn())
    return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--slices", type=int, default=960)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from pilosa_tpu.parallel.mesh import (
        SLICE_AXIS, ShardedIndex, compile_serve_count,
        compile_serve_count_batch, resolve_row_indices)

    S = args.slices
    keys_host, words_host = build_pool(S)
    mesh = Mesh(np.array(jax.devices()[:1]), (SLICE_AXIS,))
    sh = NamedSharding(mesh, P(SLICE_AXIS))
    words = jax.device_put(words_host, sh)
    mask = jax.device_put(np.ones(S, dtype=np.int32), sh)

    idx0, hit0 = resolve_row_indices(keys_host, 0)
    idx1, hit1 = resolve_row_indices(keys_host, 1)
    d = lambda a: jax.device_put(a, sh)
    idx_t = (d(idx0), d(idx1))
    hit_t = (d(hit0), d(hit1))
    words_t = (words, words)
    tree = ["and", ["leaf", 0], ["leaf", 1]]

    results = {}

    def run(name, fn, iters=None):
        it = iters or args.iters
        best_s = min(sustained(fn, it) for _ in range(args.reps))
        best_p = min(percall(fn, max(2, it // 3)) for _ in range(args.reps))
        results[name] = {"sustained_ms": best_s * 1e3,
                         "percall_ms": best_p * 1e3}
        print(f"{name:14s} sustained {best_s*1e3:8.3f} ms   "
              f"percall {best_p*1e3:8.3f} ms", flush=True)

    # -- dispatch floor
    @jax.jit
    def noop(m):
        return jnp.stack([m.sum(), m.sum()])

    run("noop", lambda: noop(mask))

    # -- HBM streaming ceiling: popcount whole pool, no gather
    @jax.jit
    def stream(w, m):
        pc = lax.population_count(w).sum(axis=(1, 2), dtype=jnp.uint32)
        pc = jnp.where(m != 0, pc, jnp.uint32(0))
        lo = (pc & jnp.uint32(0xFFFF)).astype(jnp.int32).sum()
        hi = (pc >> 16).astype(jnp.int32).sum()
        return jnp.stack([lo, hi])

    run("stream", lambda: stream(words, mask))

    # -- the real serving program
    fn_cur = compile_serve_count(mesh, tree, 2)
    run("current", lambda: fn_cur(words_t, idx_t, hit_t, mask))

    # -- gather only (no popcount fold)
    @jax.jit
    def gather_only(w, i0, h0, i1, h1, m):
        cap = w.shape[1]
        wflat = w.reshape(w.shape[0] * cap, w.shape[2])
        base = (jnp.arange(w.shape[0], dtype=jnp.int32) * cap)[:, None]
        a = wflat[(i0 + base).reshape(-1)] * h0.reshape(-1)[:, None]
        b = wflat[(i1 + base).reshape(-1)] * h1.reshape(-1)[:, None]
        s = (a.sum(dtype=jnp.uint32) + b.sum(dtype=jnp.uint32))
        return jnp.stack([s.astype(jnp.int32), s.astype(jnp.int32)])

    run("gather_only",
        lambda: gather_only(words, idx_t[0], hit_t[0], idx_t[1], hit_t[1],
                            mask))

    # -- current without the shard_map wrapper (1-device)
    @jax.jit
    def noshard(w, i0, h0, i1, h1, m):
        cap = w.shape[1]
        wflat = w.reshape(w.shape[0] * cap, w.shape[2])
        base = (jnp.arange(w.shape[0], dtype=jnp.int32) * cap)[:, None]
        a = wflat[(i0 + base).reshape(-1)] * h0.reshape(-1)[:, None]
        b = wflat[(i1 + base).reshape(-1)] * h1.reshape(-1)[:, None]
        pc = lax.population_count(a & b)
        per = pc.sum(axis=1, dtype=jnp.uint32).reshape(w.shape[0], 16).sum(
            axis=1, dtype=jnp.uint32)
        per = jnp.where(m != 0, per, jnp.uint32(0))
        lo = (per & jnp.uint32(0xFFFF)).astype(jnp.int32).sum()
        hi = (per >> 16).astype(jnp.int32).sum()
        return jnp.stack([lo, hi])

    run("noshard",
        lambda: noshard(words, idx_t[0], hit_t[0], idx_t[1], hit_t[1], mask))

    # -- contiguous-slab variant: rows start at host-known offsets and
    # their 16 containers are contiguous (dense case) -> dynamic_slice
    starts = (np.full(S, 0, dtype=np.int32), np.full(S, 16, dtype=np.int32))
    st_t = tuple(jax.device_put(s, sh) for s in starts)

    @jax.jit
    def slab(w, s0, s1, m):
        def take(start):
            def one(wrow, st):
                return lax.dynamic_slice_in_dim(wrow, st, 16, axis=0)
            return jax.vmap(one)(w, start)          # (S, 16, 2048)

        a = take(s0)
        b = take(s1)
        pc = lax.population_count(a & b).sum(axis=(1, 2), dtype=jnp.uint32)
        pc = jnp.where(m != 0, pc, jnp.uint32(0))
        lo = (pc & jnp.uint32(0xFFFF)).astype(jnp.int32).sum()
        hi = (pc >> 16).astype(jnp.int32).sum()
        return jnp.stack([lo, hi])

    run("slab", lambda: slab(words, st_t[0], st_t[1], mask))

    # -- batch-16 (amortized dispatch reference)
    fnb = compile_serve_count_batch(mesh, tree, 2, 16)
    run("batch16",
        lambda: fnb(words_t, idx_t * 16, hit_t * 16, mask),
        iters=max(4, args.iters // 4))
    results["batch16"]["per_query_ms"] = (
        results["batch16"]["sustained_ms"] / 16)

    with open("PROFILE_HEADLINE.json", "w") as f:
        json.dump({k: {kk: round(vv, 4) for kk, vv in v.items()}
                   for k, v in results.items()}, f, indent=2)
        f.write("\n")
    print(json.dumps(results, indent=2))


if __name__ == "__main__":
    main()
