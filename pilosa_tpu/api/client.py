"""InternalClient: node-to-node HTTP client (parity with
/root/reference/client.go).

Carries the three RPC planes (SURVEY.md §5): query fan-out
(execute_query with remote=True — the Executor.exec seam), bulk import,
and anti-entropy (fragment blocks / block data / attr diffs) plus
backup/restore streaming. Everything is stdlib urllib; wire bodies are
the pilosa_tpu.wire protobufs.
"""

from __future__ import annotations

import random
import threading
import time
import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple

from ..errors import DeadlineExceededError, PilosaError
from ..obs import StatMap, current_span
from ..obs import costs
from ..obs import profile as obs_profile
from ..obs.metrics import TIER_BYTES
from .. import fault
from ..wire import pb, result_from_proto, PROTOBUF_CT

# Shared transport counters (retries, breaker transitions, transport
# errors) for clients constructed without an explicit StatMap; the
# server's ClusterClient passes one snapshot-able map to every client
# so /debug/vars has a single `cluster` section.
STATS = StatMap()

# HTTP statuses treated as transient transport failures (retryable,
# breaker-countable): the node or an intermediary is overloaded/
# restarting, not telling us the request is wrong.
_TRANSIENT_STATUS = frozenset((502, 503))

# Retry backoff jitter draws don't need cryptographic strength, and a
# shared seeded Random keeps scheduling deterministic under test.
_RAND = random.Random()


class ClientError(PilosaError):
    """Transport or remote-side failure of an internal RPC.

    Structured fields (so callers classify without parsing messages):
    `host` — the node the RPC targeted; `status` — HTTP status when the
    failure was a remote response (None for transport errors);
    `transient` — True when retrying elsewhere could help (connect
    refused/reset, timeout, 502/503, breaker open), False when the
    request itself is bad (4xx: bad PQL, missing frame) and re-split
    across replicas would fail identically.
    """

    def __init__(self, msg: str, host: Optional[str] = None,
                 status: Optional[int] = None, transient: bool = False):
        super().__init__(msg)
        self.host = host
        self.status = status
        self.transient = transient


BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half-open"


class CircuitBreaker:
    """Per-node circuit breaker: closed -> open after `threshold`
    consecutive failures -> (after `cooldown` seconds) half-open, where
    exactly one probe request is admitted; probe success closes the
    breaker, probe failure re-opens it. `threshold <= 0` disables.

    The breaker is advisory backpressure for the routing layer: an open
    breaker fails calls fast with a TRANSIENT ClientError, which the
    executor's re-split treats like any dead-node error, and
    `_slices_by_node` prefers replicas whose breaker is closed."""

    def __init__(self, host: str, threshold: int = 5,
                 cooldown: float = 5.0, stats: Optional[StatMap] = None,
                 on_change=None):
        self.host = host
        self.threshold = threshold
        self.cooldown = cooldown
        self.stats = stats if stats is not None else STATS
        # on_change(host, new_state) fires on open/close edges, OUTSIDE
        # the breaker lock — the liveness feedback seam (an opening
        # breaker marks the node DOWN cluster-wide so the write path
        # stops paying timeouts to it; a close wakes hint drainers).
        self.on_change = on_change
        self._mu = threading.Lock()
        self._state = BREAKER_CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False

    def _notify(self, state: str) -> None:
        if self.on_change is None:
            return
        try:
            self.on_change(self.host, state)
        except Exception:  # noqa: BLE001 — liveness hook never breaks RPC
            pass

    @property
    def state(self) -> str:
        with self._mu:
            if (self._state == BREAKER_OPEN
                    and time.monotonic() - self._opened_at >= self.cooldown):
                return BREAKER_HALF_OPEN  # a probe would be admitted
            return self._state

    def allow(self) -> None:
        """Gate one request attempt; raises a transient ClientError
        when the breaker is open (or a half-open probe is in flight)."""
        if self.threshold <= 0:
            return
        with self._mu:
            if self._state == BREAKER_OPEN:
                if time.monotonic() - self._opened_at >= self.cooldown:
                    self._state = BREAKER_HALF_OPEN
                    self._probing = True
                    self.stats.inc("breaker.half_open")
                    return  # this caller is the probe
            elif self._state == BREAKER_HALF_OPEN:
                if not self._probing:
                    self._probing = True
                    return
            else:
                return
            self.stats.inc("breaker.reject")
            raise ClientError(
                f"{self.host}: circuit breaker open", host=self.host,
                transient=True)

    def record_success(self) -> None:
        if self.threshold <= 0:
            return
        closed = False
        with self._mu:
            if self._state != BREAKER_CLOSED:
                self.stats.inc("breaker.close")
                closed = True
            self._state = BREAKER_CLOSED
            self._failures = 0
            self._probing = False
        if closed:
            self._notify(BREAKER_CLOSED)

    def record_failure(self) -> None:
        if self.threshold <= 0:
            return
        opened = False
        with self._mu:
            self._failures += 1
            self._probing = False
            if (self._state == BREAKER_HALF_OPEN
                    or self._failures >= self.threshold):
                if self._state != BREAKER_OPEN:
                    self.stats.inc("breaker.open")
                    opened = True
                self._state = BREAKER_OPEN
                self._opened_at = time.monotonic()
        if opened:
            self._notify(BREAKER_OPEN)


class BreakerRegistry:
    """host -> CircuitBreaker, created on first use with one shared
    (threshold, cooldown, stats) policy."""

    def __init__(self, threshold: int = 5, cooldown: float = 5.0,
                 stats: Optional[StatMap] = None, on_change=None):
        self.threshold = threshold
        self.cooldown = cooldown
        self.stats = stats
        # Shared open/close hook threaded into every breaker this
        # registry creates (settable after construction — the server
        # wires it once cluster + hints exist).
        self.on_change = on_change
        self._mu = threading.Lock()
        self._by_host: Dict[str, CircuitBreaker] = {}

    def for_host(self, host: str) -> CircuitBreaker:
        with self._mu:
            b = self._by_host.get(host)
            if b is None:
                b = self._by_host[host] = CircuitBreaker(
                    host, self.threshold, self.cooldown, stats=self.stats,
                    on_change=lambda h, s: self._fire(h, s))
            return b

    def _fire(self, host: str, state: str) -> None:
        cb = self.on_change
        if cb is not None:
            cb(host, state)

    def state(self, host: str) -> str:
        with self._mu:
            b = self._by_host.get(host)
        return b.state if b is not None else BREAKER_CLOSED

    def snapshot(self) -> Dict[str, str]:
        with self._mu:
            hosts = list(self._by_host)
        return {h: self.state(h) for h in hosts}


def _host_url(host: str) -> str:
    if "://" not in host:
        host = "http://" + host
    return host.rstrip("/")


class InternalClient:
    """HTTP client bound to one remote node.

    Transient transport failures (connect refused/reset, timeout,
    502/503) are retried up to `retry_max` times with capped
    exponential backoff + jitter — within the request's remaining
    deadline budget when one is set. Every attempt is gated by and
    reported to the optional per-node `breaker`."""

    def __init__(self, host: str, timeout: float = 30.0,
                 retry_max: int = 2, retry_backoff: float = 0.05,
                 breaker: Optional[CircuitBreaker] = None,
                 stats: Optional[StatMap] = None):
        self.host = _host_url(host)
        self.timeout = timeout
        self.retry_max = retry_max
        self.retry_backoff = retry_backoff
        self.breaker = breaker
        self.stats = stats if stats is not None else STATS

    # -- low level -----------------------------------------------------------

    # Backoff for retry N (1-based) never exceeds this many seconds.
    _BACKOFF_CAP = 2.0

    def _deadline_left(self, deadline: Optional[float],
                       what: str) -> Optional[float]:
        if deadline is None:
            return None
        left = deadline - time.monotonic()
        if left <= 0:
            raise DeadlineExceededError(
                f"{what}: deadline exceeded by {-left * 1e6:.0f}us")
        return left

    def _do(self, method: str, path: str,
            params: Optional[dict] = None, body: bytes = b"",
            content_type: str = "", accept: str = "",
            headers: Optional[dict] = None,
            resp_headers: Optional[dict] = None,
            deadline: Optional[float] = None) -> Tuple[int, bytes]:
        url = self.host + path
        if params:
            url += "?" + urllib.parse.urlencode(params)
        what = f"{method} {url}"
        attempt = 0
        while True:
            left = self._deadline_left(deadline, what)
            if self.breaker is not None:
                self.breaker.allow()
            err: ClientError
            try:
                fault.point("client.do", host=self.host, method=method,
                            path=path, attempt=attempt)
                req = urllib.request.Request(url, data=body or None,
                                             method=method)
                if content_type:
                    req.add_header("Content-Type", content_type)
                if accept:
                    req.add_header("Accept", accept)
                for k, v in (headers or {}).items():
                    req.add_header(k, v)
                timeout = self.timeout
                if left is not None:
                    timeout = min(timeout, left)
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    if resp_headers is not None:
                        resp_headers.update(resp.headers.items())
                    data = resp.read()
                    if self.breaker is not None:
                        self.breaker.record_success()
                    nbytes = len(body or b"") + len(data)
                    TIER_BYTES.inc("http", nbytes)
                    # Per-call attribution under the same global
                    # counter: charges the ambient (tenant, shape)
                    # account, or the reserved system row for
                    # background legs (hint drain, anti-entropy).
                    costs.LEDGER.charge("net_http_bytes", nbytes)
                    return resp.status, data
            except urllib.error.HTTPError as e:
                data = e.read()
                if e.code not in _TRANSIENT_STATUS:
                    # The node answered: it is alive, the request is
                    # the problem. Callers raise via _check.
                    if self.breaker is not None:
                        self.breaker.record_success()
                    return e.code, data
                err = ClientError(f"{what}: status={e.code}",
                                  host=self.host, status=e.code,
                                  transient=True)
            except (urllib.error.URLError, OSError) as e:
                err = ClientError(f"{what}: {e}", host=self.host,
                                  transient=True)
                err.__cause__ = e
            # Transient failure: count it, maybe retry with backoff.
            if self.breaker is not None:
                self.breaker.record_failure()
            self.stats.inc("client.transport_error")
            attempt += 1
            if attempt > self.retry_max:
                raise err
            delay = min(self.retry_backoff * (1 << (attempt - 1)),
                        self._BACKOFF_CAP)
            delay *= 0.5 + _RAND.random()  # jitter in [0.5x, 1.5x)
            if deadline is not None \
                    and time.monotonic() + delay >= deadline:
                raise DeadlineExceededError(
                    f"{what}: deadline leaves no retry budget") from err
            self.stats.inc("client.retry")
            cur = current_span()
            if cur is not None:
                cur.tag(retries=attempt,
                        breaker_state=self.breaker.state
                        if self.breaker is not None else BREAKER_CLOSED)
            time.sleep(delay)

    def _check(self, status: int, data: bytes, what: str):
        if status >= 400:
            try:
                msg = json.loads(data.decode()).get("error", "")
            except Exception:
                msg = data[:200].decode(errors="replace")
            raise ClientError(f"{what}: status={status} {msg}",
                              host=self.host, status=status,
                              transient=status in _TRANSIENT_STATUS)

    # -- query plane ---------------------------------------------------------

    def execute_query(self, node, index: str, query: str,
                      slices: Sequence[int], remote: bool = True,
                      deadline: Optional[float] = None) -> list:
        """POST /index/{i}/query with protobuf QueryRequest, PQL
        re-serialized to a string (executor.go:1000-1083). `node` is
        accepted for interface parity with the executor seam; this
        client is already bound to one host. `deadline` is an absolute
        time.monotonic() instant: the REMAINING budget rides to the
        peer as X-Pilosa-Deadline-Us so every downstream hop inherits
        it, and bounds this call's own socket waits/retries."""
        req = pb.QueryRequest(query=query, remote=remote)
        req.slices.extend(int(s) for s in slices)
        # Trace propagation: with a span active (the executor's fan-out
        # span), ship its (trace id, span id) so the remote leg joins
        # the coordinator's trace; its spans come back as a JSON
        # response header and are grafted under the fan-out span.
        cur = current_span()
        hdrs = {}
        rhdrs: dict = {}
        if cur is not None:
            hdrs["X-Pilosa-Trace"] = \
                f"{cur.trace.trace_id}:{cur.span_id}"
        # Profile propagation mirrors the trace: with a profile active
        # (the coordinator is measuring), ask the remote leg to measure
        # too; its section comes back in X-Pilosa-Profile and merges
        # under this profile's `remotes`.
        prof = obs_profile.current()
        if prof is not None:
            hdrs["X-Pilosa-Profile"] = "1"
        if deadline is not None:
            left = deadline - time.monotonic()
            if left <= 0:
                raise DeadlineExceededError(
                    f"query to {self.host}: deadline exceeded by "
                    f"{-left * 1e6:.0f}us")
            hdrs["X-Pilosa-Deadline-Us"] = str(int(left * 1e6))
        status, data = self._do(
            "POST", f"/index/{index}/query", body=req.SerializeToString(),
            content_type=PROTOBUF_CT, accept=PROTOBUF_CT,
            headers=hdrs or None,
            resp_headers=rhdrs
            if (cur is not None or prof is not None) else None,
            deadline=deadline)
        rh_lower = {k.lower(): v for k, v in rhdrs.items()}
        if cur is not None:
            wire = rh_lower.get("x-pilosa-trace-spans", "")
            if wire:
                try:
                    cur.trace.graft(json.loads(wire), cur.span_id,
                                    node=self.host)
                except (ValueError, KeyError, TypeError):
                    pass  # malformed remote spans never fail the query
        if prof is not None:
            pwire = rh_lower.get("x-pilosa-profile", "")
            if pwire:
                try:
                    prof.merge_remote(self.host, json.loads(pwire))
                except (ValueError, KeyError, TypeError):
                    pass  # malformed remote profile never fails the query
        resp = pb.QueryResponse()
        try:
            resp.ParseFromString(data)
        except Exception:
            self._check(status, data, "query")
            raise
        if resp.err:
            # The peer answered with an application error: it is alive
            # and a replica would fail the same way (bad PQL, missing
            # frame) — non-transient, so the executor propagates it
            # instead of re-splitting.
            raise ClientError(resp.err, host=self.host, transient=False)
        self._check(status, data, "query")
        return [result_from_proto(r) for r in resp.results]

    # -- import plane --------------------------------------------------------

    def import_bits(self, index: str, frame: str, slice_: int,
                    row_ids: Sequence[int], column_ids: Sequence[int],
                    timestamps: Optional[Sequence[int]] = None,
                    remote: bool = False):
        """POST /import protobuf ImportRequest (client.go:304-390).
        `remote=True` marks the batch already-coordinated (a replica
        leg of a quorum import or a hint replay): the receiver applies
        it locally without re-fanning-out to the other owners."""
        req = pb.ImportRequest(index=index, frame=frame, slice=slice_)
        req.row_ids.extend(int(r) for r in row_ids)
        req.column_ids.extend(int(c) for c in column_ids)
        if timestamps:
            req.timestamps.extend(int(t) for t in timestamps)
        status, data = self._do("POST", "/import",
                                params={"remote": "true"} if remote
                                else None,
                                body=req.SerializeToString(),
                                content_type=PROTOBUF_CT)
        self._check(status, data, "import")

    def export_csv(self, index: str, frame: str, view: str,
                   slice_: int) -> str:
        status, data = self._do("GET", "/export", params={
            "index": index, "frame": frame, "view": view, "slice": slice_})
        self._check(status, data, "export")
        return data.decode()

    # -- schema / status -----------------------------------------------------

    def schema(self) -> List[dict]:
        status, data = self._do("GET", "/schema")
        self._check(status, data, "schema")
        return json.loads(data.decode())["indexes"]

    def max_slices(self, inverse: bool = False) -> Dict[str, int]:
        params = {"inverse": "true"} if inverse else None
        status, data = self._do("GET", "/slices/max", params=params)
        self._check(status, data, "slices/max")
        return {k: int(v)
                for k, v in json.loads(data.decode())["maxSlices"].items()}

    def frame_views(self, index: str, frame: str) -> List[str]:
        status, data = self._do("GET", f"/index/{index}/frame/{frame}/views")
        self._check(status, data, "views")
        return json.loads(data.decode())["views"]

    def fragment_nodes(self, index: str, slice_: int) -> List[dict]:
        status, data = self._do("GET", "/fragment/nodes",
                                params={"index": index, "slice": slice_})
        self._check(status, data, "fragment/nodes")
        return json.loads(data.decode())

    def node_status(self) -> pb.NodeStatus:
        """GET /internal/status — gossip-lite state pull."""
        status, data = self._do("GET", "/internal/status")
        self._check(status, data, "internal/status")
        msg = pb.NodeStatus()
        msg.ParseFromString(data)
        return msg

    def send_message(self, data: bytes):
        """POST a framed broadcast message to /internal/message."""
        status, resp = self._do("POST", "/internal/message", body=data,
                                content_type="application/octet-stream")
        self._check(status, resp, "internal/message")

    def epoch_digest(self) -> dict:
        """GET /internal/epochs — the peer's replication-epoch digest:
        {"host", "epochs": {fragment key -> epoch}, "queue_depth"}.
        Raises ClientError on transport failure; an older peer without
        the endpoint surfaces as a 404 ClientError the status-poll
        caller tolerates."""
        status, data = self._do("GET", "/internal/epochs")
        self._check(status, data, "internal/epochs")
        return json.loads(data.decode())

    def advance_epochs(self, epochs: dict,
                       deadline: Optional[float] = None) -> int:
        """POST /internal/epochs/advance — floor-raise the peer's
        fragment epochs to reconciled values (after hint replay /
        anti-entropy convergence). Returns the number of fragments the
        peer actually raised."""
        body = json.dumps({"epochs": {str(k): int(v)
                                      for k, v in epochs.items()}})
        status, data = self._do("POST", "/internal/epochs/advance",
                                body=body.encode(),
                                content_type="application/json",
                                deadline=deadline)
        self._check(status, data, "internal/epochs/advance")
        try:
            return int(json.loads(data.decode()).get("applied", 0))
        except ValueError:
            return 0

    # -- anti-entropy plane --------------------------------------------------

    def fragment_blocks(self, index: str, frame: str, view: str,
                        slice_: int,
                        deadline: Optional[float] = None,
                        ) -> List[Tuple[int, bytes]]:
        """GET /fragment/blocks -> [(block id, checksum)]; a replica
        that has not created the fragment yet reads as empty (client.go
        FragmentBlocks ErrFragmentNotFound tolerance,
        fragment.go:1345). `deadline` is an absolute time.monotonic()
        instant bounding socket waits and retries (the anti-entropy
        loop must never hang on one sick peer)."""
        status, data = self._do("GET", "/fragment/blocks", params={
            "index": index, "frame": frame, "view": view, "slice": slice_},
            deadline=deadline)
        if status == 404:
            return []
        self._check(status, data, "fragment/blocks")
        return [(int(b["id"]), bytes.fromhex(b["checksum"]))
                for b in json.loads(data.decode())["blocks"]]

    def block_data(self, index: str, frame: str, view: str, slice_: int,
                   block: int,
                   deadline: Optional[float] = None,
                   ) -> Tuple[List[int], List[int]]:
        """GET /fragment/block/data -> (row_ids, column_ids)
        (client.go:849-888), deadline-bounded like fragment_blocks."""
        req = pb.BlockDataRequest(index=index, frame=frame, view=view,
                                  slice=slice_, block=block)
        status, data = self._do("GET", "/fragment/block/data",
                                body=req.SerializeToString(),
                                content_type=PROTOBUF_CT, accept=PROTOBUF_CT,
                                deadline=deadline)
        if status == 404:
            return [], []  # fragment not created on this replica yet
        self._check(status, data, "fragment/block/data")
        resp = pb.BlockDataResponse()
        resp.ParseFromString(data)
        return list(resp.row_ids), list(resp.column_ids)

    def column_attr_diff(self, index: str,
                         blocks: List[Tuple[int, bytes]]) -> Dict[int, dict]:
        return self._attr_diff(f"/index/{index}/attr/diff", blocks)

    def row_attr_diff(self, index: str, frame: str,
                      blocks: List[Tuple[int, bytes]]) -> Dict[int, dict]:
        return self._attr_diff(f"/index/{index}/frame/{frame}/attr/diff",
                               blocks)

    def _attr_diff(self, path: str,
                   blocks: List[Tuple[int, bytes]]) -> Dict[int, dict]:
        body = json.dumps({"blocks": [{"id": bid, "checksum": cs.hex()}
                                      for bid, cs in blocks]}).encode()
        status, data = self._do("POST", path, body=body,
                                content_type="application/json")
        self._check(status, data, "attr/diff")
        return {int(k): v
                for k, v in json.loads(data.decode())["attrs"].items()}

    # -- backup / restore ----------------------------------------------------

    def fragment_data(self, index: str, frame: str, view: str,
                      slice_: int) -> Optional[bytes]:
        """GET /fragment/data tar; None when the fragment doesn't exist
        (client.go BackupSlice 404 handling)."""
        status, data = self._do("GET", "/fragment/data", params={
            "index": index, "frame": frame, "view": view, "slice": slice_})
        if status == 404:
            return None
        self._check(status, data, "fragment/data")
        return data

    def restore_fragment(self, index: str, frame: str, view: str,
                         slice_: int, tar_bytes: bytes):
        status, data = self._do("POST", "/fragment/data", params={
            "index": index, "frame": frame, "view": view, "slice": slice_},
            body=tar_bytes, content_type="application/octet-stream")
        self._check(status, data, "fragment/data")

    # -- membership control plane --------------------------------------------

    def cluster_resize(self, action: str, **fields) -> dict:
        """POST /cluster/resize?remote=true — ship a membership control
        message (join/leave/cutover/complete) to a peer. remote=true
        marks it already-coordinated so the peer applies it locally
        without re-forwarding (no broadcast loops)."""
        body = json.dumps(dict(fields, action=action)).encode()
        status, data = self._do("POST", "/cluster/resize",
                                params={"remote": "true"}, body=body,
                                content_type="application/json")
        self._check(status, data, "cluster/resize")
        return json.loads(data.decode() or "{}")

    def backup_frame(self, index: str, frame: str, view: str,
                     max_slice: int) -> List[Tuple[int, bytes]]:
        """Pull every existing fragment tar of a (frame, view)
        (client.go BackupTo 463-545)."""
        out = []
        for s in range(max_slice + 1):
            data = self.fragment_data(index, frame, view, s)
            if data is not None:
                out.append((s, data))
        return out

    def create_index(self, index: str, **options):
        body = json.dumps({"options": options}).encode() if options else b"{}"
        status, data = self._do("POST", f"/index/{index}", body=body,
                                content_type="application/json")
        if status != 409:
            self._check(status, data, "create index")

    def create_frame(self, index: str, frame: str, **options):
        body = json.dumps({"options": options}).encode() if options else b"{}"
        status, data = self._do("POST", f"/index/{index}/frame/{frame}",
                                body=body, content_type="application/json")
        if status != 409:
            self._check(status, data, "create frame")
