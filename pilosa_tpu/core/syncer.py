"""Active anti-entropy: HolderSyncer + FragmentSyncer.

Parity with /root/reference/holder.go:364-562 and fragment.go:1300-1481:
walk every index/frame/view/slice this node owns; diff attr-store block
checksums and fragment block checksums against every replica; pull
divergent block data, majority-merge, and push SetBit/ClearBit PQL
diffs back to the remotes that are missing consensus bits.

`client_factory(host)` yields an InternalClient (or any object with the
same attr-diff / fragment-blocks / block-data / execute_query surface —
tests inject fakes, the mockable-collective-layer pattern from
SURVEY.md §4).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Tuple

from .. import fault
from .view import VIEW_INVERSE, VIEW_STANDARD


def _count(stats, name: str, n: int = 1):
    """Duck-typed counter bump: ExpvarStats has .count, StatMap has
    .inc, tests may pass neither."""
    if stats is None or n == 0:
        return
    if hasattr(stats, "count"):
        stats.count(name, n)
    elif hasattr(stats, "inc"):
        stats.inc(name, n)


class Closing:
    """Cooperative cancellation flag shared with the server's close path
    (reference closing chan semantics)."""

    def __init__(self):
        self._event = threading.Event()

    def close(self):
        self._event.set()

    @property
    def closed(self) -> bool:
        return self._event.is_set()

    def is_set(self) -> bool:
        """threading.Event-compatible alias (Holder.warm stop flag)."""
        return self._event.is_set()

    def wait(self, timeout: float) -> bool:
        return self._event.wait(timeout)


class FragmentSyncer:
    """Syncs one fragment across its replica set
    (fragment.go:1300-1481)."""

    def __init__(self, fragment, host: str, nodes,
                 client_factory: Callable, closing: Optional[Closing] = None,
                 logger=None, row_label: str = "rowID",
                 column_label: str = "columnID", stats=None,
                 op_deadline: float = 0.0):
        self.fragment = fragment
        self.host = host
        self.nodes = nodes  # replica owner Nodes incl. self
        self.client_factory = client_factory
        self.closing = closing or Closing()
        self.logger = logger
        # The frame's actual labels (the reference hardcodes the
        # defaults, fragment.go:1462-1466, which breaks custom labels —
        # deliberately fixed here).
        self.row_label = row_label
        self.column_label = column_label
        # Anti-entropy counters (blocks scanned/dirty/merged, peers
        # skipped) — server passes its ExpvarStats so /metrics exports
        # them; None is fine for embedded use.
        self.stats = stats
        # Per-RPC budget in seconds for peer block fetches; 0 = none.
        # Only forwarded when set, so client fakes without a deadline
        # kwarg keep working.
        self.op_deadline = float(op_deadline)

    def _log(self, msg: str):
        if self.logger is not None:
            self.logger.info(msg)

    def _peers(self) -> List[str]:
        return [n.host for n in self.nodes if n.host != self.host]

    def _deadline_kw(self) -> dict:
        if self.op_deadline > 0:
            return {"deadline": time.monotonic() + self.op_deadline}
        return {}

    def sync_fragment(self):
        """Compare block checksums across replicas; merge every block
        that differs anywhere (fragment.go:1320-1399). An unreachable
        replica is SKIPPED, not fatal — one dead peer must not abort
        the whole anti-entropy pass for the live ones."""
        f = self.fragment
        local = dict(f.blocks())
        remote_sets = []
        for host in self._peers():
            if self.closing.closed:
                return
            client = self.client_factory(host)
            try:
                blocks = dict(client.fragment_blocks(
                    f.index, f.frame, f.view, f.slice,
                    **self._deadline_kw()))
            except Exception as e:  # noqa: BLE001 — skip unreachable peers
                _count(self.stats, "syncer_peers_skipped")
                self._log(f"sync {f.index}/{f.frame}/{f.view}/{f.slice}: "
                          f"peer {host} unreachable, skipping: {e}")
                continue
            remote_sets.append((host, blocks))

        # Block ids where any replica disagrees with local (either side
        # missing, or checksums differ).
        dirty = set()
        converged = []  # peers whose block map matched local exactly
        for host, blocks in remote_sets:
            peer_dirty = False
            for bid, cs in blocks.items():
                if local.get(bid) != cs:
                    dirty.add(bid)
                    peer_dirty = True
            for bid, cs in local.items():
                if blocks.get(bid) != cs:
                    dirty.add(bid)
                    peer_dirty = True
            if not peer_dirty:
                converged.append(host)

        scanned = {bid for _, blocks in remote_sets for bid in blocks}
        scanned.update(local)
        _count(self.stats, "syncer_blocks_scanned", len(scanned))
        _count(self.stats, "syncer_blocks_dirty", len(dirty))
        for bid in sorted(dirty):
            if self.closing.closed:
                return
            self.sync_block(bid)
        self._reconcile_epochs(converged)

    def _reconcile_epochs(self, hosts: List[str]) -> None:
        """Replication-epoch reconcile (read-repair raises the loser's
        numbering to the winner's): replicas converge on CONTENT via
        the merges above, but each node's fragment epoch is a local
        counter — two bit-identical replicas can disagree on it, and
        the coordinator's staleness judge fails closed on the lower
        one forever. For every peer whose block map matched local
        EXACTLY (checksum-proven identical — a peer that just took
        diff pushes waits for the next pass, so an epoch never runs
        ahead of the bits it vouches for), floor-raise its epoch to
        ours. advance_epoch is monotone, so pushing to a peer that is
        actually ahead is a no-op there."""
        f = self.fragment
        epoch = int(getattr(f, "epoch", 0) or 0)
        if not epoch or not hosts:
            return
        key = f"{f.index}/{f.frame}/{f.view}/{f.slice}"
        for host in hosts:
            if self.closing.closed:
                return
            client = self.client_factory(host)
            advance = getattr(client, "advance_epochs", None)
            if advance is None:
                continue  # test fakes / older peers: digest-only
            try:
                advance({key: epoch})
                _count(self.stats, "syncer_epochs_reconciled")
            except Exception as e:  # noqa: BLE001 — advisory; the
                # digest stays conservative until a later pass.
                self._log(f"sync {key}: epoch reconcile to {host} "
                          f"failed: {e}")

    def sync_block(self, block_id: int):
        """Majority-merge one block and push diffs to remotes
        (fragment.go:1401-1481). Peer fetches ride the injected
        client's retry/breaker path, bounded by `op_deadline`; an
        unreachable peer contributes nothing to consensus instead of
        aborting the merge."""
        f = self.fragment
        fault.point("syncer.block", index=f.index, frame=f.frame,
                    view=f.view, slice=f.slice, block=block_id)
        peers = []
        data = []
        for host in self._peers():
            client = self.client_factory(host)
            try:
                rows, cols = client.block_data(
                    f.index, f.frame, f.view, f.slice, block_id,
                    **self._deadline_kw())
            except Exception as e:  # noqa: BLE001 — skip unreachable peers
                _count(self.stats, "syncer_peers_skipped")
                self._log(f"sync block {block_id}: peer {host} "
                          f"unreachable, skipping: {e}")
                continue
            peers.append(host)
            data.append((rows, cols))

        diffs = f.merge_block(block_id, data)
        _count(self.stats, "syncer_blocks_merged")

        # Push consensus diffs to each remote as SetBit/ClearBit PQL —
        # only for the standard view, whose orientation SetBit speaks
        # (fragment.go:1458-1477 "Only sync the standard block"; other
        # views converge via their own local merges on each replica).
        if f.view != VIEW_STANDARD:
            return
        base = f.slice * _slice_width()
        for host, ((set_rows, set_cols), (clear_rows, clear_cols)) in zip(
                peers, diffs):
            calls = []
            for r, c in zip(set_rows, set_cols):
                calls.append(self._bit_pql("SetBit", int(r), base + int(c)))
            for r, c in zip(clear_rows, clear_cols):
                calls.append(self._bit_pql("ClearBit", int(r), base + int(c)))
            if not calls:
                continue
            client = self.client_factory(host)
            try:
                client.execute_query(None, f.index, "".join(calls), [],
                                     remote=True)
            except Exception as e:  # noqa: BLE001 — peer died mid-sync;
                # its replica converges on a later pass.
                _count(self.stats, "syncer_peers_skipped")
                self._log(f"sync block {block_id}: diff push to {host} "
                          f"failed: {e}")

    def _bit_pql(self, name: str, row_id: int, column_id: int) -> str:
        f = self.fragment
        return (f"{name}(frame={f.frame!r}, {self.row_label}={row_id}, "
                f"{self.column_label}={column_id})")


class HolderSyncer:
    """Cluster-wide anti-entropy walk (holder.go:364-562)."""

    def __init__(self, holder, host: str, cluster,
                 client_factory: Callable, closing: Optional[Closing] = None,
                 logger=None, stats=None, op_deadline: float = 0.0):
        self.holder = holder
        self.host = host
        self.cluster = cluster
        self.client_factory = client_factory
        self.closing = closing or Closing()
        self.logger = logger
        self.stats = stats
        self.op_deadline = float(op_deadline)

    def _log(self, msg: str):
        if self.logger is not None:
            self.logger.info(msg)

    def sync_holder(self):
        """Walk the schema, syncing attrs and owned fragments
        (holder.go:385-436)."""
        for index_name in sorted(self.holder.indexes):
            if self.closing.closed:
                return
            idx = self.holder.index(index_name)
            if idx is None:
                continue
            self.sync_index(idx)
            max_slices = {
                VIEW_STANDARD: idx.max_slice(),
                VIEW_INVERSE: idx.max_inverse_slice(),
            }
            for frame_name in sorted(idx.frames):
                f = idx.frame(frame_name)
                if f is None:
                    continue
                self.sync_frame(index_name, f)
                for view in list(f.views.values()):
                    is_inv = view.name == VIEW_INVERSE or \
                        view.name.startswith(VIEW_INVERSE + "_")
                    limit = max_slices[VIEW_INVERSE if is_inv
                                       else VIEW_STANDARD]
                    for slice_ in range(limit + 1):
                        if self.closing.closed:
                            return
                        if not self.cluster.owns_fragment(
                                self.host, index_name, slice_):
                            continue
                        self.sync_fragment(index_name, f.name, view.name,
                                           slice_)

    def sync_index(self, idx):
        """Column-attr block diff against every other node
        (holder.go:439-481)."""
        self._sync_attrs(idx.column_attr_store,
                         lambda client, blocks:
                         client.column_attr_diff(idx.name, blocks))

    def sync_frame(self, index_name: str, frame):
        """Row-attr block diff (holder.go:484-528)."""
        self._sync_attrs(frame.row_attr_store,
                         lambda client, blocks:
                         client.row_attr_diff(index_name, frame.name, blocks))

    def _sync_attrs(self, store, diff_fn):
        for node in self.cluster.nodes:
            if node.host == self.host or self.closing.closed:
                continue
            client = self.client_factory(node.host)
            try:
                attrs = diff_fn(client, store.blocks())
            except Exception as e:  # noqa: BLE001 — skip unreachable peers
                self._log(f"attr sync with {node.host} failed: {e}")
                continue
            if attrs:
                store.set_bulk_attrs(attrs)

    def sync_fragment(self, index: str, frame: str, view: str, slice_: int):
        """Ensure the fragment exists locally, then replica-sync it
        (holder.go:531-562)."""
        f = self.holder.frame(index, frame)
        if f is None:
            return
        v = f.create_view_if_not_exists(view)
        frag = v.create_fragment_if_not_exists(slice_)
        nodes = self.cluster.fragment_nodes(index, slice_)
        idx = self.holder.index(index)
        syncer = FragmentSyncer(frag, self.host, nodes,
                                self.client_factory, self.closing,
                                self.logger, row_label=f.row_label,
                                column_label=idx.column_label,
                                stats=self.stats,
                                op_deadline=self.op_deadline)
        try:
            syncer.sync_fragment()
        except Exception as e:  # noqa: BLE001 — sync is best-effort
            self._log(f"fragment sync {index}/{frame}/{view}/{slice_} "
                      f"failed: {e}")


def _slice_width() -> int:
    from .. import SLICE_WIDTH
    return SLICE_WIDTH
