"""Gossip membership + broadcast plane (parallel/gossip.py).

The analog of the reference's memberlist-backed GossipNodeSet
(gossip/gossip.go): join via state push/pull, SWIM probe liveness,
epidemic send_async, direct-TCP send_sync, NodeStatus state exchange.
All nodes run in-process on loopback ephemeral ports (reference
pattern: real engines, fake transport distances — client_test.go:30-43).
"""

import time

import pytest

from pilosa_tpu.parallel.gossip import ALIVE, DEAD, GossipNodeSet
from pilosa_tpu.wire import pb


def wait_until(fn, timeout=8.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


class RecordingHandler:
    """broadcast_handler + status_handler test double."""

    def __init__(self, host=""):
        self.host = host
        self.messages = []
        self.remote_statuses = []

    def receive_message(self, msg):
        self.messages.append(msg)

    def local_status(self):
        ns = pb.NodeStatus()
        ns.host = self.host
        return ns

    def handle_remote_status(self, status):
        self.remote_statuses.append(status)


def make_node(name, seeds=(), **kw):
    h = RecordingHandler(host=name)
    kw.setdefault("probe_interval", 0.05)
    kw.setdefault("probe_timeout", 0.1)
    kw.setdefault("push_pull_interval", 10.0)
    kw.setdefault("gossip_port", 0)
    g = GossipNodeSet(local_host=name, bind="127.0.0.1",
                      seeds=seeds, broadcast_handler=h, status_handler=h,
                      **kw)
    g.open()
    return g, h


class TestMembership:
    def test_join_two_nodes(self):
        a, _ = make_node("a:1")
        b, _ = make_node("b:1", seeds=[a.gossip_addr])
        try:
            assert wait_until(lambda: a.nodes() == ["a:1", "b:1"])
            assert wait_until(lambda: b.nodes() == ["a:1", "b:1"])
        finally:
            a.close()
            b.close()

    def test_three_nodes_transitive_join(self):
        """c joins via b only, but must learn a through gossip state."""
        a, _ = make_node("a:1")
        b, _ = make_node("b:1", seeds=[a.gossip_addr])
        assert wait_until(lambda: len(b.nodes()) == 2)
        c, _ = make_node("c:1", seeds=[b.gossip_addr])
        try:
            want = ["a:1", "b:1", "c:1"]
            for g in (a, b, c):
                assert wait_until(lambda: g.nodes() == want), (
                    g.local_host, g.nodes())
        finally:
            for g in (a, b, c):
                g.close()

    def test_dead_node_detected(self):
        a, _ = make_node("a:1", suspicion_mult=2.0)
        b, _ = make_node("b:1", seeds=[a.gossip_addr], suspicion_mult=2.0)
        assert wait_until(lambda: len(a.nodes()) == 2)
        b.close()
        try:
            assert wait_until(lambda: a.nodes() == ["a:1"], timeout=15.0)
            with a._lock:
                assert a._members["b:1"].state == DEAD
        finally:
            a.close()

    def test_on_change_fires(self):
        seen = []
        a, _ = make_node("a:1")
        a.on_change = lambda hosts: seen.append(list(hosts))
        b, _ = make_node("b:1", seeds=[a.gossip_addr])
        try:
            assert wait_until(lambda: ["a:1", "b:1"] in seen)
        finally:
            a.close()
            b.close()


class TestStatePushPull:
    def test_join_exchanges_node_status(self):
        a, ha = make_node("a:1")
        b, hb = make_node("b:1", seeds=[a.gossip_addr])
        try:
            # Join is a synchronous push/pull: both sides see a NodeStatus.
            assert wait_until(lambda: ha.remote_statuses
                              and hb.remote_statuses)
            assert ha.remote_statuses[0].host == "b:1"
            assert hb.remote_statuses[0].host == "a:1"
        finally:
            a.close()
            b.close()


class TestBroadcast:
    def _msg(self, name="idx-x"):
        m = pb.CreateIndexMessage()
        m.index = name
        return m

    def test_send_sync_direct(self):
        a, _ = make_node("a:1")
        b, hb = make_node("b:1", seeds=[a.gossip_addr])
        try:
            assert wait_until(lambda: len(a.nodes()) == 2)
            a.send_sync(self._msg())
            assert wait_until(lambda: len(hb.messages) == 1)
            assert hb.messages[0].index == "idx-x"
        finally:
            a.close()
            b.close()

    def test_send_sync_raises_on_dead_peer(self):
        a, _ = make_node("a:1")
        b, _ = make_node("b:1", seeds=[a.gossip_addr])
        assert wait_until(lambda: len(a.nodes()) == 2)
        b.close()
        try:
            with pytest.raises(ConnectionError):
                a.send_sync(self._msg())
        finally:
            a.close()

    def test_send_async_epidemic(self):
        """send_async piggybacks on probes and reaches every node,
        including ones not directly probed by the sender."""
        a, ha = make_node("a:1")
        b, hb = make_node("b:1", seeds=[a.gossip_addr])
        c, hc = make_node("c:1", seeds=[a.gossip_addr])
        try:
            for g in (a, b, c):
                assert wait_until(lambda: len(g.nodes()) == 3)
            a.send_async(self._msg("epidemic"))
            assert wait_until(lambda: hb.messages and hc.messages,
                              timeout=15.0)
            assert hb.messages[0].index == "epidemic"
            assert hc.messages[0].index == "epidemic"
            # Sender must not deliver to itself.
            assert not ha.messages
        finally:
            for g in (a, b, c):
                g.close()

    def test_async_delivered_once(self):
        a, _ = make_node("a:1")
        b, hb = make_node("b:1", seeds=[a.gossip_addr])
        try:
            assert wait_until(lambda: len(a.nodes()) == 2)
            a.send_async(self._msg("once"))
            assert wait_until(lambda: hb.messages)
            time.sleep(0.5)  # let retransmits flow
            assert len(hb.messages) == 1
        finally:
            a.close()
            b.close()


class TestRefutation:
    def test_false_suspicion_refuted(self):
        a, _ = make_node("a:1", suspicion_mult=20.0)
        b, _ = make_node("b:1", seeds=[a.gossip_addr], suspicion_mult=20.0)
        try:
            assert wait_until(lambda: len(a.nodes()) == 2)
            # Inject a false suspicion of b into a's view.
            with b._lock:
                inc = b._incarnation
            a._apply_down("suspect", "b:1", inc)
            with a._lock:
                assert a._members["b:1"].state == "suspect"
            # b hears the gossip, refutes with a higher incarnation,
            # and a flips it back to alive.
            def alive_again():
                with a._lock:
                    m = a._members["b:1"]
                    return m.state == ALIVE and m.incarnation > inc
            assert wait_until(alive_again, timeout=15.0)
        finally:
            a.close()
            b.close()


class TestReviewRegressions:
    def _msg(self, name):
        m = pb.CreateIndexMessage()
        m.index = name
        return m

    def test_repeated_sync_broadcast_delivered_every_time(self):
        """Identical sync messages (create/delete/create of one index)
        must each land — the epidemic dedupe must not eat them."""
        a, _ = make_node("a:1")
        b, hb = make_node("b:1", seeds=[a.gossip_addr])
        try:
            assert wait_until(lambda: len(a.nodes()) == 2)
            a.send_sync(self._msg("same"))
            a.send_sync(self._msg("same"))
            assert wait_until(lambda: len(hb.messages) == 2)
        finally:
            a.close()
            b.close()

    def test_seed_down_at_open_is_retried(self):
        """A node whose seed is unreachable at open() must keep retrying
        and join once the seed appears."""
        import socket as socket_mod
        # Reserve an address for the future seed.
        probe = socket_mod.socket(socket_mod.AF_INET, socket_mod.SOCK_STREAM)
        probe.bind(("127.0.0.1", 0))
        seed_addr = probe.getsockname()
        probe.close()
        b, _ = make_node("b:1", seeds=[seed_addr], probe_interval=0.05)
        try:
            assert b.nodes() == ["b:1"]  # isolated
            a, _ = make_node("a:1", gossip_port=seed_addr[1])
            try:
                assert wait_until(
                    lambda: b.nodes() == ["a:1", "b:1"], timeout=15.0)
            finally:
                a.close()
        finally:
            b.close()
