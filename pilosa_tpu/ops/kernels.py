"""Pallas TPU kernels for the fused roaring set-op + popcount path.

TPU re-design of the reference's POPCNT assembly kernels
(/root/reference/roaring/assembly_amd64.s:25-115: popcntAndSlice etc.):
the pairwise bitwise op and the population-count reduction run in one
kernel over VMEM-resident blocks, streaming from HBM via the grid, with a
scalar accumulator in SMEM. Backend dispatch (Pallas on TPU, fused XLA
elsewhere) is the analog of the reference's hasAsm runtime dispatch
(roaring/assembly_asm.go:20).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .bitops import BINARY_OPS, count_pair, fold_tree
from .pool import CONTAINER_WORDS

# Rows of 2048-word containers processed per grid step (512 KB/input block).
_BLOCK_M = 64


def use_pallas() -> bool:
    """True when the Pallas TPU path should be used.

    Measured on a real v5e chip (960-slice 1B-column Intersect+Count,
    2026-07): XLA flat-gather 5.1 ms, Pallas streaming kernel 7.4 ms —
    the slab scan's multiple launches each pay the dispatch floor, so
    XLA stays the default count backend (PILOSA_TPU_COUNT_BACKEND=pallas
    opts in; both backends are hardware-validated and differentially
    tested). This dispatch gate covers the pairwise kernels, where
    Pallas wins."""
    return jax.default_backend() == "tpu"


def _pair_count_kernel(op_name: str, a_ref, b_ref, o_ref):
    op = BINARY_OPS[op_name]

    @pl.when(pl.program_id(0) == 0)
    def _init():
        o_ref[0, 0] = jnp.int32(0)

    o_ref[0, 0] += jnp.sum(
        lax.population_count(op(a_ref[:], b_ref[:])).astype(jnp.int32)
    )


@functools.partial(jax.jit, static_argnames=("op", "interpret"))
def _pallas_pair_count(a, b, op: str = "and", interpret: bool = False):
    m = a.shape[0]
    grid = (max(1, (m + _BLOCK_M - 1) // _BLOCK_M),)
    # Zero-pad to a block multiple: padding contributes no set bits for
    # any of the four ops (0 op 0 == 0).
    padded = grid[0] * _BLOCK_M
    if padded != m:
        pad = ((0, padded - m), (0, 0))
        a = jnp.pad(a, pad)
        b = jnp.pad(b, pad)
    out = pl.pallas_call(
        functools.partial(_pair_count_kernel, op),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_M, CONTAINER_WORDS), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK_M, CONTAINER_WORDS), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        interpret=interpret,
    )(a, b)
    return out[0, 0]


def fused_pair_count(a, b, op: str = "and", *, force_pallas: bool | None = None,
                     interpret: bool = False):
    """popcount(op(a, b)) over (M, 2048) uint32 blocks, fused on device.

    Dispatches to the Pallas TPU kernel on TPU backends, fused XLA
    elsewhere. `force_pallas`/`interpret` exist for differential tests.
    """
    a = a.reshape(-1, CONTAINER_WORDS)
    b = b.reshape(-1, CONTAINER_WORDS)
    if force_pallas or (force_pallas is None and use_pallas()):
        return _pallas_pair_count(a, b, op=op, interpret=interpret)
    return count_pair(a, b, op)


# -- fused call-tree count with in-kernel container gather -------------------
#
# The XLA mesh path gathers each leaf row into a fresh (16, 2048) block
# before combining (parallel/plan.py eval_tree over pool.words[idx]),
# which materializes the gathered copies in HBM: for the 1B-column
# Intersect+Count that triples the memory traffic. This kernel instead
# streams the EXACT containers straight from the pool into VMEM via
# scalar-prefetched index maps (the Pallas block-sparse pattern), so
# each container is read once and nothing intermediate is written.

# Container words viewed as (sublanes, lanes) for the TPU tiling rules:
# a Pallas block's minor two dims must be (8k, 128k)-aligned, so a
# 2048-word container streams as a (16, 128) tile.
_SUBLANES = 16
_LANES = 128


def _tree_count_kernel(tree, num_leaves, idx_ref, hit_ref, *refs):
    o_ref = refs[num_leaves]
    s = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when((s == 0) & (j == 0))
    def _init():
        o_ref[0, 0] = jnp.int32(0)

    def leaf(i):
        blk = refs[i][0, 0, :, :]
        keep = hit_ref[i, s, j] != 0
        return jnp.where(keep, blk, jnp.uint32(0))

    o_ref[0, 0] += jnp.sum(
        lax.population_count(fold_tree(tree, leaf)).astype(jnp.int32))


# SMEM budget for one pallas_call's scalar-prefetch tables: the
# (L, S, 16) idx+hit tables live in SMEM (1 MB/core) — at 960 slices
# and 2 leaves they overflow it (observed: "Used 1.88M of 1.00M smem"),
# so larger shards run slice slabs, each its own kernel launch. A
# 2-leaf/256-slice slab (128 KB of tables) compiles with headroom; the
# slab size scales down with leaf count to hold that table budget.
_PREFETCH_SLICES_PER_LEAF = 512


def _tree_count_call(words4, idx, hit, tree, num_leaves, interpret):
    """One pallas_call over (S, cap, 16, 128) words with (L, S, 16)
    prefetch tables."""
    s_n, r_n = idx.shape[1], idx.shape[2]

    def leaf_spec(leaf):
        return pl.BlockSpec(
            (1, 1, _SUBLANES, _LANES),
            lambda s, j, idx_ref, hit_ref, leaf=leaf: (
                s, idx_ref[leaf, s, j], 0, 0))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s_n, r_n),
        in_specs=[leaf_spec(leaf) for leaf in range(num_leaves)],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
    )
    out = pl.pallas_call(
        functools.partial(_tree_count_kernel, tree, num_leaves),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.int32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(idx, hit, *([words4] * num_leaves))
    return out[0, 0]


def _coarse_count_kernel(tree, num_leaves, starts_ref, *refs):
    o_ref = refs[num_leaves]
    s = pl.program_id(0)

    def leaf(i):
        blk = refs[i][0, 0, :, :]
        keep = starts_ref[i, s] >= 0
        return jnp.where(keep, blk, jnp.uint32(0))

    o_ref[0, s] = jnp.sum(
        lax.population_count(fold_tree(tree, leaf)).astype(jnp.int32))


def coarse_count_per_slice(views, starts, tree, *,
                           interpret: bool = False):
    """ONE pallas_call producing per-slice coarse counts.

    The shared engine under both coarse count surfaces — the
    mesh-level scalar kernel below and the serving-layer program
    (mesh.compile_serve_count_coarse_pallas), which differ only in
    whether leaves share one pool and how the per-slice counts are
    reduced (scalar sum vs 16-bit limb psum).

    views:  tuple per leaf of (S, R_i, 16*16, 128) uint32 row-run
            views (each leaf may have its own pool/capacity).
    starts: (L, S) int32 signed row-run index; negative = absent or
            masked out (the block is read clipped and zeroed).
    Returns (1, S) int32 per-slice counts (each <= 2^20, exact)."""
    num_leaves, s_n = starts.shape

    def leaf_spec(leaf):
        return pl.BlockSpec(
            (1, 1, 16 * _SUBLANES, _LANES),
            lambda s, starts_ref, leaf=leaf: (
                s, jnp.maximum(starts_ref[leaf, s], 0), 0, 0))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(s_n,),
        in_specs=[leaf_spec(leaf) for leaf in range(num_leaves)],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
    )
    return pl.pallas_call(
        functools.partial(_coarse_count_kernel, tree, num_leaves),
        out_shape=jax.ShapeDtypeStruct((1, s_n), jnp.int32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(starts, *views)


def tree_count_pallas_coarse(words, starts, tree, *,
                             interpret: bool = False):
    """Fused popcount(eval_tree) over COARSE whole-row runs — ONE
    pallas_call for ANY slice count (VERDICT r4 #2).

    The general kernel above needs (L, S, 16) idx+hit prefetch tables;
    at headline scale they overflow the 1 MB SMEM budget and force a
    lax.scan of slab launches, each paying the dispatch floor — the
    measured reason it lost to the XLA gather path (7.4 ms vs 5.1 ms on
    the 960-slice Intersect+Count). When every leaf row is staged as
    one contiguous 16-aligned container run (mesh.coarse_row_starts —
    true for dense rows, which staging sorts and pads), the per-slice
    address state collapses to ONE signed int per (leaf, slice): the
    row-run index, negative where the slice holds no part of the row.
    That is 1/48th the SMEM (4 bytes vs 2x16x4), so even a 3072-slice
    x 8-leaf TABLE fits one launch with headroom, and each grid step
    streams each leaf's whole 128 KB row run from HBM exactly once —
    no gathered intermediate is ever written back (the XLA path's ~3x
    traffic overhead, kernels.py header note).

    Count range: the scalar accumulator is int32, exact to 2^31-1 set
    bits per SHARD (~2048 fully-dense slices) — the same bound as the
    general kernel above and the XLA mesh path. >2^31-bit shards are
    the SERVING layer's regime, whose programs split per-slice counts
    into 16-bit limbs before the psum (compile_serve_count*,
    combine_limbs) precisely for that.

    words:  (S, cap, 2048) uint32 pool, cap % 16 == 0.
    starts: (L, S) int32 signed row-run index (pos // 16, or any
            negative where absent/masked out).
    tree:   nested op list with numbered leaves (plan._tree_signature).

    Returns the shard's total count as a scalar int32.
    """
    num_leaves, s_n = starts.shape
    cap = words.shape[1]
    assert cap % 16 == 0, cap
    # One block = one whole row run: 16 containers x 2048 words viewed
    # as a (256, 128) tile — minor dims satisfy the (8, 128) rule.
    words5 = words.reshape(s_n, cap // 16, 16 * _SUBLANES, _LANES)
    per_slice = coarse_count_per_slice(
        (words5,) * num_leaves, starts, tree, interpret=interpret)
    return per_slice.sum(dtype=jnp.int32)


def tree_count_pallas(words, idx, hit, tree, *, interpret: bool = False):
    """Fused popcount(eval_tree) over one shard's container pool.

    words: (S, cap, 2048) uint32 — the local slices' pools.
    idx:   (L, S, 16) int32 — per leaf/slice/sub-key container index
           into `cap` (clipped; garbage where hit == 0).
    hit:   (L, S, 16) int32 — 1 where the container is really present.
    tree:  nested op list with numbered leaves (plan._tree_signature).

    Returns the shard's total count as a scalar int32. Shards whose
    prefetch tables exceed the SMEM budget run fixed-size slice slabs
    via lax.scan plus one remainder call — a fixed slab (not a divisor
    of S) so a prime slice count can't degrade to per-slice launches.
    """
    num_leaves, s_n, r_n = idx.shape
    cap = words.shape[1]
    # (S, cap, 16, 128): per-container blocks whose minor dims satisfy
    # the TPU (8, 128) tiling constraint — (1, 1, 2048) blocks do not.
    words4 = words.reshape(s_n, cap, _SUBLANES, _LANES)

    chunk = max(1, _PREFETCH_SLICES_PER_LEAF // num_leaves)
    if s_n <= chunk:
        return _tree_count_call(words4, idx, hit, tree, num_leaves, interpret)

    c, rem = divmod(s_n, chunk)
    main = c * chunk
    words_r = words4[:main].reshape(c, chunk, cap, _SUBLANES, _LANES)
    idx_r = idx[:, :main].reshape(num_leaves, c, chunk, r_n).transpose(
        1, 0, 2, 3)
    hit_r = hit[:, :main].reshape(num_leaves, c, chunk, r_n).transpose(
        1, 0, 2, 3)

    def body(acc, xs):
        w, ix, ht = xs
        return acc + _tree_count_call(w, ix, ht, tree, num_leaves,
                                      interpret), None

    acc, _ = lax.scan(body, jnp.int32(0), (words_r, idx_r, hit_r))
    if rem:
        acc = acc + _tree_count_call(words4[main:], idx[:, main:],
                                     hit[:, main:], tree, num_leaves,
                                     interpret)
    return acc
