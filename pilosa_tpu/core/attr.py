"""AttrStore: durable id -> attribute-map storage with checksummed blocks.

Parity with /root/reference/attr.go (BoltDB there, stdlib sqlite3 here):
values limited to str/int/bool/float (attr.go:35-40); SetAttrs merges
into existing maps; 100-id blocks expose checksums so replicas can diff
and sync only divergent blocks (attr.go:181-241, holder.go:439-528).
"""

from __future__ import annotations

import hashlib
import json
import os
import sqlite3
import threading
from typing import Dict, List, Optional, Tuple

# IDs per checksummed block (reference attr.go:32).
ATTR_BLOCK_SIZE = 100

_ALLOWED = (str, int, bool, float)


def _validate(attrs: dict) -> dict:
    for k, v in attrs.items():
        if v is not None and not isinstance(v, _ALLOWED):
            raise TypeError(f"invalid attr type for {k!r}: {type(v).__name__}")
    return attrs


def _key(id_: int) -> str:
    # Zero-padded so lexicographic order == numeric order for uint64.
    return f"{id_:020d}"


class AttrStore:
    """sqlite-backed attribute store with an in-memory cache."""

    def __init__(self, path: str):
        self.path = path
        self._db: Optional[sqlite3.Connection] = None
        self._cache: Dict[int, dict] = {}
        self._lock = threading.RLock()

    def open(self):
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._db = sqlite3.connect(self.path, check_same_thread=False)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS attrs (id TEXT PRIMARY KEY, data TEXT NOT NULL)"
        )
        self._db.commit()

    def close(self):
        if self._db is not None:
            self._db.close()
            self._db = None
        self._cache.clear()

    def attrs(self, id_: int) -> dict:
        with self._lock:
            if id_ in self._cache:
                return dict(self._cache[id_])
            row = self._db.execute(
                "SELECT data FROM attrs WHERE id = ?", (_key(id_),)
            ).fetchone()
            m = json.loads(row[0]) if row else {}
            self._cache[id_] = m
            return dict(m)

    def set_attrs(self, id_: int, m: dict):
        """Merge m into id's attrs; None values delete keys (attr.go:118)."""
        _validate(m)
        with self._lock:
            cur = self.attrs(id_)
            for k, v in m.items():
                if v is None:
                    cur.pop(k, None)
                else:
                    cur[k] = v
            self._db.execute(
                "INSERT OR REPLACE INTO attrs (id, data) VALUES (?, ?)",
                (_key(id_), json.dumps(cur, sort_keys=True)),
            )
            self._db.commit()
            self._cache[id_] = cur

    def set_bulk_attrs(self, items: Dict[int, dict]):
        with self._lock:
            for id_, m in items.items():
                _validate(m)
            for id_, m in items.items():
                cur = self.attrs(id_)
                cur.update({k: v for k, v in m.items() if v is not None})
                for k, v in m.items():
                    if v is None:
                        cur.pop(k, None)
                self._db.execute(
                    "INSERT OR REPLACE INTO attrs (id, data) VALUES (?, ?)",
                    (_key(id_), json.dumps(cur, sort_keys=True)),
                )
                self._cache[id_] = cur
            self._db.commit()

    # -- anti-entropy blocks ----------------------------------------------

    def _rows(self) -> List[Tuple[int, str]]:
        return [
            (int(k), data)
            for k, data in self._db.execute("SELECT id, data FROM attrs ORDER BY id")
        ]

    def blocks(self) -> List[Tuple[int, bytes]]:
        """[(block_id, checksum)] over 100-id blocks (attr.go:181-209)."""
        out: List[Tuple[int, bytes]] = []
        h = None
        cur_block = None
        for id_, data in self._rows():
            blk = id_ // ATTR_BLOCK_SIZE
            if blk != cur_block:
                if h is not None:
                    out.append((cur_block, h.digest()))
                cur_block, h = blk, hashlib.sha1()
            h.update(_key(id_).encode())
            h.update(data.encode())
        if h is not None:
            out.append((cur_block, h.digest()))
        return out

    def block_data(self, block_id: int) -> Dict[int, dict]:
        """All attrs in one block (attr.go:212-241)."""
        lo, hi = block_id * ATTR_BLOCK_SIZE, (block_id + 1) * ATTR_BLOCK_SIZE
        return {
            id_: json.loads(data)
            for id_, data in self._rows()
            if lo <= id_ < hi
        }


def diff_blocks(
    local: List[Tuple[int, bytes]], remote: List[Tuple[int, bytes]]
) -> List[int]:
    """Block ids where remote differs from local (reference
    AttrBlocks.Diff, attr.go:398-428): present only remotely, or both
    present with different checksums."""
    lmap = dict(local)
    out = []
    for blk, sum_ in remote:
        if lmap.get(blk) != sum_:
            out.append(blk)
    return sorted(out)
