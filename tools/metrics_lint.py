"""pilosa-tpu metrics lint — conventions gate for the /metrics surface.

Builds an in-process node (Holder + Executor + Handler over a test
cluster), drives representative traffic through every serving path the
registry bridges, scrapes /metrics live, and asserts the exposition
keeps its contract:

  1. every family has HELP text — a metric nobody can read the meaning
     of is a metric nobody can alert on;
  2. conventional suffixes: counters end in `_total`, histograms carry
     a unit (`_us` / `_microseconds` / `_seconds` / `_bytes`), gauges
     never impersonate counters with a `_total` suffix, and nobody
     sneaks in a nonstandard unit (`_ms`, `_msec`, `_millis`);
  3. no unbounded label keys: every label key must come from the known
     bounded vocabulary below — a new key (say, a query string or a
     trace id used as a label) is a cardinality leak and fails the
     lint until it is consciously added here;
  4. per-family series-count ceiling (--max-series) as a tripwire for
     label products that exploded.

Run by CI against the live scrape (tier-1 workflow); also usable
against a running node with --url, or a saved exposition with --file.
Exit code 0 = clean, 1 = violations (listed one per line).
"""

from __future__ import annotations

import argparse
import os
import re
import sys
import tempfile
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Label keys with *bounded* cardinality by construction. Keys bounded
# by config or membership (host, target, device, index, frame, tenant)
# are included: their growth tracks operator action, not request
# content. Anything outside this set fails the lint.
ALLOWED_LABEL_KEYS = frozenset((
    "le",            # histogram buckets (fixed log2 ladder)
    "backend",       # serving routes (fixed set)
    "tier",          # local | ici | http
    "tenant",        # [sched] tenant-weights + default + other
    "outcome",       # SLO outcome vocabulary
    "route",         # SLO route vocabulary
    "phase",         # profiler phase names (code-defined)
    "mode",          # dispatch modes (code-defined)
    "reason",        # fallback/veto/eviction reasons (code-defined)
    "event",         # cache event names (code-defined)
    "entry",         # compile entry points (code-defined)
    "device",        # device ids (hardware-bounded)
    "objective",     # SLO objectives (code-defined)
    "window",        # SLO windows (code-defined)
    "state",         # breaker/membership states (code-defined)
    "level",         # write-consistency levels (code-defined)
    "op",            # descriptor ops (code-defined)
    "version",       # build info
    "path",          # scheduler admission paths (code-defined)
    "index",         # schema-bounded
    "frame",         # schema-bounded
    "view",          # schema-bounded (standard | bsi.<field>)
    "slice",         # per-fragment expvar bridge (data-bounded)
    "host",          # ring-membership-bounded
    "target",        # hint targets (ring-membership-bounded)
    "kind",          # stat kinds (code-defined)
    "subsystem",     # liveness-plane heartbeat names (code-defined)
    "tag",           # expvar bare-tag bridge
    "value",         # expvar string-set info bridge
    "replica",       # read-path pick: owner | follower | fallback_owner
    "staleness",     # read class: strict | bounded
    "cache",         # result-cache interaction: hit | miss | verify
    "shape",         # query-shape signatures (flight-ring-bounded)
    "dimension",     # regression-watch dimensions (code-defined)
    "account",       # cost-ledger event rows (code-defined)
))

# Families whose label product includes the query-shape signature.
# Shape cardinality is bounded by the flight ring / ledger account
# caps (default 256 accounts, x tier for the net family), not by the
# general --max-series default — they get a dedicated ceiling sized to
# the caps. A cost family sailing past it means the LRU fold stopped
# working.
SHAPE_LABELED_PREFIXES = ("pilosa_cost_", "pilosa_perf_regression")
SHAPE_SERIES_CEILING = 2048

# The liveness plane's per-subsystem gauge: one series per registered
# heartbeat. Heartbeat names are code-defined (a dozen or so loops),
# so a family sailing past this means someone is registering
# per-request or per-fragment heartbeats — a leak, not growth.
HEALTH_STATE_FAMILY = "pilosa_health_state"
HEALTH_STATE_CEILING = 64

# Suffixes that carry a recognized unit for histogram families.
# `_size` is the dimensionless-count ladder (e.g. writes per WAL group
# commit) — a real unit would be wrong there.
HIST_UNIT_SUFFIXES = ("_us", "_microseconds", "_seconds", "_bytes",
                      "_size")

# Nonstandard unit suffixes nobody should introduce (the repo
# standardized on µs for latency and raw bytes for sizes).
BANNED_SUFFIXES = ("_ms", "_msec", "_millis", "_milliseconds",
                   "_kb", "_mb", "_gb")


def parse_exposition(text: str):
    """(families, series) from Prometheus 0.0.4 text. `families` maps
    name -> {"type": ..., "help": ...}; `series` maps family name ->
    list of (sample name, label dict)."""
    families: Dict[str, Dict[str, Optional[str]]] = {}
    series: Dict[str, List[Tuple[str, dict]]] = {}
    label_re = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
    sample_re = re.compile(
        r"([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)(?:\s+#.*)?$")
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, name, help_text = line.split(" ", 3)
            families.setdefault(name, {"type": None, "help": None})
            families[name]["help"] = help_text
            continue
        if line.startswith("# TYPE "):
            _, _, name, mtype = line.split(" ", 3)
            families.setdefault(name, {"type": None, "help": None})
            families[name]["type"] = mtype
            continue
        if line.startswith("#"):
            continue
        m = sample_re.match(line)
        if m is None:
            continue
        sname, rawlabels, _ = m.groups()
        # Histogram expansions belong to their base family.
        fname = sname
        for suf in ("_bucket", "_sum", "_count"):
            if sname.endswith(suf) and sname[: -len(suf)] in families:
                fname = sname[: -len(suf)]
                break
        labels = dict(label_re.findall(rawlabels or ""))
        series.setdefault(fname, []).append((sname, labels))
    return families, series


def lint(text: str, max_series: int = 500) -> List[str]:
    """All convention violations in one exposition, one per entry."""
    problems: List[str] = []
    families, series = parse_exposition(text)
    for name, meta in sorted(families.items()):
        mtype = meta["type"]
        if not meta["help"]:
            problems.append(f"{name}: missing HELP text")
        if mtype is None:
            problems.append(f"{name}: missing TYPE line")
        if mtype == "counter" and not name.endswith("_total"):
            problems.append(
                f"{name}: counter families must end in _total")
        if mtype == "gauge" and name.endswith("_total"):
            problems.append(
                f"{name}: gauge with a counter's _total suffix")
        if mtype == "histogram" and not name.endswith(
                HIST_UNIT_SUFFIXES):
            problems.append(
                f"{name}: histogram lacks a unit suffix "
                f"({'/'.join(HIST_UNIT_SUFFIXES)})")
        for banned in BANNED_SUFFIXES:
            if name.endswith(banned):
                problems.append(
                    f"{name}: nonstandard unit suffix {banned} "
                    f"(standardize on _us / _seconds / _bytes)")
        rows = series.get(name, [])
        ceiling = max_series
        if name.startswith(SHAPE_LABELED_PREFIXES):
            ceiling = SHAPE_SERIES_CEILING
        if name == HEALTH_STATE_FAMILY:
            ceiling = HEALTH_STATE_CEILING
        if len(rows) > ceiling:
            problems.append(
                f"{name}: {len(rows)} series exceeds the "
                f"ceiling of {ceiling}")
        seen_keys = set()
        for _, labels in rows:
            seen_keys.update(labels)
        for key in sorted(seen_keys - ALLOWED_LABEL_KEYS):
            problems.append(
                f"{name}: label key {key!r} not in the bounded "
                f"vocabulary (tools/metrics_lint.py "
                f"ALLOWED_LABEL_KEYS)")
    return problems


def live_scrape() -> str:
    """Build an in-process node, drive every bridged path once, and
    return its /metrics text (exemplars on — the lint must hold for
    the OpenMetrics variant too)."""
    from pilosa_tpu.api import Handler
    from pilosa_tpu.core import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.parallel import new_test_cluster

    with tempfile.TemporaryDirectory() as td:
        holder = Holder(os.path.join(td, "data"))
        holder.open()
        try:
            cluster = new_test_cluster(1)
            ex = Executor(holder, host=cluster.nodes[0].host,
                          cluster=cluster, use_device=False)
            h = Handler(holder, ex, cluster=cluster,
                        host=cluster.nodes[0].host)
            assert h.handle("POST", "/index/i").status == 200
            assert h.handle("POST", "/index/i/frame/f").status == 200
            assert h.handle(
                "POST", "/index/i/query",
                body=b"SetBit(rowID=1, frame=f, columnID=5)",
            ).status == 200
            for _ in range(3):
                assert h.handle(
                    "POST", "/index/i/query",
                    body=b"Count(Bitmap(rowID=1, frame=f))",
                ).status == 200
            assert h.handle("POST", "/index/i/query",
                            body=b"TopN(frame=f, n=2)").status == 200
            # Bounded-staleness read: exercises the follower-read
            # pick counters (pilosa_read_replica_total{replica,
            # staleness}) and the result-cache families.
            # rowID differs from the strict Counts above so the query
            # memo can't swallow the placement.
            assert h.handle(
                "POST", "/index/i/query",
                body=b"Count(Bitmap(rowID=2, frame=f))",
                headers={"x-pilosa-staleness": "100ms"},
            ).status == 200
            # Tenant-attributed traffic: populates the cost-ledger
            # families (pilosa_cost_*{tenant,shape}) so the lint
            # covers their label vocabulary, and confirms the
            # /debug/costs endpoint is backed by the same ledger.
            assert h.handle(
                "POST", "/index/i/query",
                body=b"Count(Bitmap(rowID=1, frame=f))",
                headers={"x-pilosa-tenant": "lint"},
            ).status == 200
            costs = h.handle("GET", "/debug/costs",
                             params={"sort": "device_us"})
            assert costs.status == 200
            assert b"accounts" in costs.body
            resp = h.handle("GET", "/metrics",
                            params={"exemplars": "true"})
            assert resp.status == 200
            text = resp.body.decode()
            assert "pilosa_cost_queries_total" in text, \
                "cost ledger families missing from the live scrape"
            return text
        finally:
            holder.close()


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="metrics_lint",
        description="lint a /metrics exposition for convention drift")
    src = ap.add_mutually_exclusive_group()
    src.add_argument("--url", help="scrape a running node's /metrics")
    src.add_argument("--file", help="lint a saved exposition file")
    ap.add_argument("--max-series", type=int, default=500,
                    help="per-family series ceiling (default 500)")
    args = ap.parse_args(argv)
    if args.url:
        import urllib.request

        with urllib.request.urlopen(args.url, timeout=10) as resp:
            text = resp.read().decode()
    elif args.file:
        with open(args.file) as f:
            text = f.read()
    else:
        text = live_scrape()
    problems = lint(text, max_series=args.max_series)
    for p in problems:
        print(p)
    nfam = len(parse_exposition(text)[0])
    if problems:
        print(f"metrics lint: {len(problems)} violation(s) across "
              f"{nfam} families", file=sys.stderr)
        return 1
    print(f"metrics lint: {nfam} families clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
