"""Pure-XLA bitwise ops over dense (…, 2048)-word blocks.

These are the jnp reference semantics for the Pallas kernels in
kernels.py (differential-test pairing, the analog of the reference's
asm-vs-Go suite, /root/reference/roaring/assembly_test.go) and the
fallback path on non-TPU backends. XLA fuses the elementwise op with the
popcount reduction, which already beats the reference's
materialize-then-count Count path (SURVEY.md §3.2 note).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Bitwise combiners by PQL-level name.
BINARY_OPS = {
    "and": jnp.bitwise_and,
    "or": jnp.bitwise_or,
    "xor": jnp.bitwise_xor,
    "andnot": lambda a, b: jnp.bitwise_and(a, jnp.bitwise_not(b)),
}


def popcount_words(words: jax.Array) -> jax.Array:
    """Total set bits in a word block (reference popcntSliceAsm,
    roaring/assembly_amd64.s:25-44). int32: a fragment holds <= 2^20 bits
    per row; cross-slice totals are aggregated host-side in Python ints."""
    return jax.lax.population_count(words).astype(jnp.int32).sum()


def count_pair(a: jax.Array, b: jax.Array, op: str = "and") -> jax.Array:
    """Fused popcount(op(a, b)) without materializing the result to HBM
    (reference popcnt{And,Or,Xor,Mask}SliceAsm, assembly_amd64.s:47-115)."""
    return jax.lax.population_count(BINARY_OPS[op](a, b)).astype(jnp.int32).sum()


def dense_row_count(row: jax.Array) -> jax.Array:
    """Bit count of one materialized dense row block."""
    return popcount_words(row)


def flat_fold_op(tree):
    """The single combining op of a depth-one tree whose leaves appear
    in index order (``(op, (leaf,0), (leaf,1), ...)``) — the shape the
    native fused fold kernel accepts — or None for anything nested,
    unary, or reordered."""
    if tree[0] == "leaf" or len(tree) < 3:
        return None
    for i, child in enumerate(tree[1:]):
        if child[0] != "leaf" or child[1] != i:
            return None
    return tree[0]


def fold_tree(tree, leaf_fn):
    """Fold a numbered op-shape tree (plan._tree_signature) over
    `leaf_fn(leaf_index) -> block`, combining with the n-ary bitwise
    semantics shared by every backend (XLA eval_tree, the Pallas
    tree-count kernel). One combiner, so backends cannot drift."""
    if tree[0] == "leaf":
        return leaf_fn(tree[1])
    vals = [fold_tree(c, leaf_fn) for c in tree[1:]]
    acc = vals[0]
    for v in vals[1:]:
        if tree[0] == "and":
            acc = acc & v
        elif tree[0] == "or":
            acc = acc | v
        else:  # andnot
            acc = acc & ~v
    return acc


# -- sorted-array (roaring array-container) count kernels ---------------------
#
# Device analog of the reference's array×array and array×bitmap kernel
# classes (roaring.go:1270-1351 intersectionCountArrayArray /
# intersectionCountArrayBitmap): containers staged as sorted u16 value
# lists instead of 2048 packed words. Layout contract shared with
# mesh.build_sparse_sharded_index:
#   vals  (..., K) sorted ascending within the first `len` entries,
#         padded with 0xFFFF (>= every real value, so sortedness holds);
#   lens  (...,)   real cardinality per container.
# A real value of 65535 colliding with the padding is handled by the
# `pos < len_b` guard, never by the pad value itself — the kernels are
# exact for every u16 value.


def _row_searchsorted(b, x):
    """Batched searchsorted-left: per row, insertion positions of x's
    entries into sorted b. b, x: (..., K) int32. A statically unrolled
    binary search (log2 K steps of take_along_axis) — jnp.searchsorted
    is 1-D and a vmap over S*16 containers traces slowly; this is one
    fused gather ladder."""
    k = b.shape[-1]
    lo = jnp.zeros(x.shape, dtype=jnp.int32)
    hi = jnp.full(x.shape, k, dtype=jnp.int32)
    for _ in range(max(1, k.bit_length())):
        mid = (lo + hi) >> 1
        bm = jnp.take_along_axis(b, jnp.minimum(mid, k - 1), axis=-1)
        open_ = lo < hi  # converged rows must not advance past k
        right = (bm < x) & open_
        lo = jnp.where(right, mid + 1, lo)
        hi = jnp.where(right | ~open_, hi, mid)
    return lo


def sparse_pair_intersect_counts(a_vals, a_len, b_vals, b_len):
    """Per-container |a ∩ b| for batched sorted-array containers — the
    XLA variant of the array×array intersect-count kernel.

    a_vals/b_vals: (..., K) int32 (or any int dtype; cast by caller),
    sorted with 0xFFFF padding; a_len/b_len: (...,) int32 real lengths.
    Returns (...,) int32 intersection cardinalities. O(K log K) gathers
    per container vs the dense kernel's O(2048) word pass — the win is
    entirely in bytes touched (K*2 vs 8192 per operand)."""
    ka = a_vals.shape[-1]
    kb = b_vals.shape[-1]  # operands may come from different pools
    a = a_vals.astype(jnp.int32)
    b = b_vals.astype(jnp.int32)
    pos = _row_searchsorted(b, a)
    bm = jnp.take_along_axis(b, jnp.minimum(pos, kb - 1), axis=-1)
    valid_a = jnp.arange(ka, dtype=jnp.int32) < a_len[..., None]
    hit = (bm == a) & (pos < b_len[..., None]) & valid_a
    return hit.sum(axis=-1, dtype=jnp.int32)


def sparse_probe_intersect_counts(a_vals, a_len, b_words):
    """Per-container |a ∩ b| where a is a sorted-array container and b
    a packed-word bitmap container — the mixed array×bitmap probe path
    (reference intersectionCountArrayBitmap class). a_vals: (..., K)
    int, a_len: (...,), b_words: (..., CONTAINER_WORDS) uint32 (zeroed
    where the container is absent). Each a-value probes one word and
    one bit; padding probes land somewhere harmless and are masked by
    valid_a."""
    k = a_vals.shape[-1]
    a = a_vals.astype(jnp.int32) & 0xFFFF  # pad 0xFFFF probes word 2047
    w = jnp.take_along_axis(b_words, (a >> 5).astype(jnp.int32), axis=-1)
    bit = (w >> (a & 31).astype(jnp.uint32)) & jnp.uint32(1)
    valid_a = jnp.arange(k, dtype=jnp.int32) < a_len[..., None]
    return jnp.where(valid_a, bit.astype(jnp.int32), 0).sum(
        axis=-1, dtype=jnp.int32)


def sparse_op_counts(op: str, inter, na, nb):
    """Per-container set-op cardinality from |a∩b| and the operand
    cardinalities (inclusion–exclusion) — how the sorted-array path
    serves every BINARY_OPS member with ONE intersect kernel:
    |a∪b| = |a|+|b|-|a∩b|, |a\\b| = |a|-|a∩b|, |aΔb| = |a|+|b|-2|a∩b|.
    na/nb must already be zeroed for absent containers (hit-masked),
    and inter is 0 whenever either side is absent."""
    if op == "and":
        return inter
    if op == "or":
        return na + nb - inter
    if op == "andnot":
        return na - inter
    if op == "xor":
        return na + nb - 2 * inter
    raise ValueError(f"unknown sparse op: {op!r}")


def sparse_pair_count_host(a: "object", b: "object") -> int:
    """Host reference |a ∩ b| of two sorted numpy value arrays — the
    baseline side of the sparse differential suite (and the honest
    bench baseline when ops/native is absent)."""
    import numpy as np

    return int(np.intersect1d(np.asarray(a), np.asarray(b),
                              assume_unique=True).size)
