"""Fault-injection harness: named injection points wired into the
client and executor so tests (and operators) can inject timeouts,
connection resets, slow responses, and mid-query node death without
monkeypatching internals.

Library code calls `fault.point("client.do", host=..., ...)` at each
seam; with nothing armed that is one module-global truthiness check.
Faults are armed either programmatically::

    rule = fault.arm("client.do", error=ConnectionResetError,
                     times=2, host="127.0.0.1:10101")
    ...
    fault.reset()

or from the environment (parsed once, at first use or via
`fault.load_env()`)::

    PILOSA_TPU_FAULT="client.do:error=ConnectionError,host=h:1,times=3;\
handler.query:delay=500ms,host=h:2"
    PILOSA_TPU_FAULT_SEED=0   # seeds the prob= draw schedule

Rule knobs: `error=` (exception class, instance, or builtin name),
`delay=` (seconds or Go duration — fires as a sleep, composable with
error), `times=N` (fire at most N times), `after=N` (skip the first N
matches — "die mid-query"), `prob=P` (fire with probability P drawn
from ONE seeded RNG, so a fixed PILOSA_TPU_FAULT_SEED makes the whole
chaos schedule deterministic), plus any `key=value` context match
(e.g. `host=`) compared against the kwargs the injection point passes.

Two knobs arm DATA faults rather than raise/sleep faults — their rules
never fire at plain `point()` seams:

    bits=N / offset=K / xor=M   bit rot: `fault.corrupt(name, data)`
                      seams return `data` with N bits flipped at
                      seeded-random positions (or the single byte at
                      offset K XORed with M, default 0x01; K counts
                      from the end when negative). Deterministic under
                      PILOSA_TPU_FAULT_SEED.
    delta=N           result perturbation: `fault.perturb(name, value)`
                      seams return value+N — a device fold that
                      silently miscomputes, for shadow-verification
                      tests.

Injection points currently wired:

    client.do         every InternalClient HTTP attempt (host, method,
                      path) — including each retry attempt
    handler.query     server side of POST /index/{i}/query (host,
                      index, remote) — a delay here is a slow node
    executor.fanout   coordinator-side remote fan-out (node)
    sched.admit       query-scheduler admission (tenant) — a delay
                      here is a stalled scheduler; an error (e.g. an
                      armed sched.AdmissionError instance) forces
                      deterministic sheds
    syncer.block      anti-entropy per-block merge (index, frame,
                      view, slice, block) — a delay here is a slow
                      sync pass; an error aborts one block's merge
    rebalance.transfer  one fragment migration attempt (index, frame,
                      view, slice, target) — errors exercise the
                      transfer retry/backoff path
    storage.fsync     before every WAL commit fsync (kind="commit",
                      path, pending) and before the snapshot temp-file
                      fsync (kind="snapshot", path) — an armed error
                      whose constructor SIGKILLs the process simulates
                      power loss at the exact durability boundary
    storage.rename    before the snapshot's atomic os.replace (path)
    storage.import_apply  after a bulk import's in-memory apply,
                      before it is made durable (path) — errors
                      exercise the reload-from-disk recovery
    mesh.stage        before a fragment view is built + H2D-staged
                      (index, frame, view, slices) — an armed
                      ResourceExhausted simulates device OOM during
                      staging and exercises evict-and-retry
    device.exec       before each device program launch (sig, kind) —
                      an armed ResourceExhausted here exercises the
                      full recovery ladder: evict + retry, host-fold
                      fallback, and plan-signature quarantine; a
                      `delta=` rule perturbs the returned count at the
                      result seam (kind="count-result"), driving the
                      shadow-verification catch path
    storage.corrupt   fragment file reads (path, kind="snapshot" for
                      the main file, kind="side-wal" for the snapshot
                      side log) — a `bits=`/`offset=` rule flips bits
                      in the bytes read, simulating at-rest bit rot
    watchdog.stall    inside every registered heartbeat's beat()
                      (subsystem) and before each SPMD descriptor
                      dispatch (subsystem="spmd-dispatch", op) — a
                      `delay=` rule wedges that loop mid-iteration
                      with its heartbeat stale, the deterministic
                      hang the liveness watchdog must detect; e.g.
                      `watchdog.stall:delay=2,subsystem=hint-drain`

Every fired fault is counted in `fault.STATS` and recorded in the
bounded `fault.log()` ring for assertions.
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Type

from .obs import StatMap

class SimulatedResourceExhausted(RuntimeError):
    """Stands in for jaxlib's XlaRuntimeError(RESOURCE_EXHAUSTED) at
    the mesh.stage / device.exec seams — the serve layer's OOM
    classifier matches it by message, exactly as it matches the real
    thing, so CPU-only chaos tests drive the same recovery ladder a
    TPU OOM would."""

    def __init__(self, msg: str = ""):
        super().__init__(
            f"RESOURCE_EXHAUSTED: {msg or 'fault-injected device OOM'}")


# Exception names accepted by the env spec (error=Name).
_ERROR_NAMES: Dict[str, Type[BaseException]] = {
    "ConnectionError": ConnectionError,
    "ConnectionResetError": ConnectionResetError,
    "ConnectionRefusedError": ConnectionRefusedError,
    "TimeoutError": TimeoutError,
    "OSError": OSError,
    "ResourceExhausted": SimulatedResourceExhausted,
}

STATS = StatMap()


class Rule:
    """One armed fault. Mutable counters are guarded by the registry
    lock; reads of the immutable spec fields are free."""

    __slots__ = ("point", "error", "delay", "times", "after", "prob",
                 "match", "fired", "seen", "bits", "offset", "xor",
                 "delta")

    def __init__(self, point: str, error=None, delay: float = 0.0,
                 times: Optional[int] = None, after: int = 0,
                 prob: float = 1.0, match: Optional[Dict[str, Any]] = None,
                 bits: int = 0, offset: Optional[int] = None,
                 xor: int = 0x01, delta: Optional[int] = None):
        self.point = point
        self.error = error
        self.delay = float(delay)
        self.times = times  # None = unbounded
        self.after = int(after)
        self.prob = float(prob)
        self.match = dict(match or {})
        self.bits = int(bits)          # corrupt(): random bit flips
        self.offset = offset           # corrupt(): fixed byte offset
        self.xor = int(xor)            # corrupt(): mask for offset mode
        self.delta = delta             # perturb(): value shift
        self.fired = 0  # times this rule actually fired
        self.seen = 0   # times this rule matched (incl. after/prob skips)

    def _is_data_rule(self) -> bool:
        """Corrupt/perturb rules act only at their own seams — a plain
        point() must not raise, sleep, or burn their times= budget."""
        return self.bits > 0 or self.offset is not None or self.delta is not None

    def _matches(self, ctx: Dict[str, Any]) -> bool:
        return all(str(ctx.get(k)) == str(v) for k, v in self.match.items())

    def _make_error(self) -> BaseException:
        err = self.error
        if isinstance(err, BaseException):
            return err
        if isinstance(err, type) and issubclass(err, BaseException):
            return err(f"fault injected at {self.point}")
        return ConnectionError(f"fault injected at {self.point}: {err}")


class Injector:
    """Registry of armed rules + the seeded schedule RNG."""

    def __init__(self, seed: Optional[int] = None):
        self._mu = threading.Lock()
        self._rules: List[Rule] = []
        if seed is None:
            env = os.environ.get("PILOSA_TPU_FAULT_SEED", "")
            seed = int(env) if env else 0
        self._rand = random.Random(seed)
        self._log: "deque[tuple]" = deque(maxlen=256)

    def arm(self, point: str, *, error=None, delay: float = 0.0,
            times: Optional[int] = None, after: int = 0, prob: float = 1.0,
            match: Optional[Dict[str, Any]] = None, bits: int = 0,
            offset: Optional[int] = None, xor: int = 0x01,
            delta: Optional[int] = None, **ctx_match) -> Rule:
        m = dict(match or {})
        m.update(ctx_match)
        rule = Rule(point, error=error, delay=delay, times=times,
                    after=after, prob=prob, match=m, bits=bits,
                    offset=offset, xor=xor, delta=delta)
        with self._mu:
            self._rules.append(rule)
        _set_active(True)
        return rule

    def disarm(self, rule: Rule) -> None:
        with self._mu:
            if rule in self._rules:
                self._rules.remove(rule)
            empty = not self._rules
        if empty:
            _set_active(False)

    def reset(self, seed: Optional[int] = None) -> None:
        """Drop every rule and (optionally) reseed the schedule."""
        with self._mu:
            self._rules.clear()
            self._log.clear()
            if seed is not None:
                self._rand = random.Random(seed)
        _set_active(False)

    def log(self) -> List[tuple]:
        """Recent fired faults: (point, ctx dict) newest last."""
        with self._mu:
            return list(self._log)

    def fire(self, point: str, ctx: Dict[str, Any]) -> None:
        """Evaluate every rule for `point`; sleeps/raises per the first
        delay/error rule that fires (delay rules all sleep, then at
        most one error raises)."""
        to_raise: Optional[BaseException] = None
        delay = 0.0
        with self._mu:
            for rule in self._rules:
                if rule.point != point or rule._is_data_rule() \
                        or not rule._matches(ctx):
                    continue
                rule.seen += 1
                if rule.seen <= rule.after:
                    continue
                if rule.times is not None and rule.fired >= rule.times:
                    continue
                if rule.prob < 1.0 and self._rand.random() >= rule.prob:
                    continue
                rule.fired += 1
                self._log.append((point, dict(ctx)))
                STATS.inc(f"fault.{point}")
                if rule.delay > 0.0:
                    delay = max(delay, rule.delay)
                if rule.error is not None and to_raise is None:
                    to_raise = rule._make_error()
        if delay > 0.0:
            time.sleep(delay)
        if to_raise is not None:
            raise to_raise

    def mutate(self, point: str, data: bytes, ctx: Dict[str, Any]) -> bytes:
        """Apply every armed bit-rot rule for `point` to `data`.
        Flip positions come from the ONE seeded RNG, so a fixed
        PILOSA_TPU_FAULT_SEED makes the rot schedule deterministic."""
        buf = None
        with self._mu:
            for rule in self._rules:
                if rule.point != point or not rule._matches(ctx):
                    continue
                if rule.bits <= 0 and rule.offset is None:
                    continue
                rule.seen += 1
                if rule.seen <= rule.after:
                    continue
                if rule.times is not None and rule.fired >= rule.times:
                    continue
                if rule.prob < 1.0 and self._rand.random() >= rule.prob:
                    continue
                if not data:
                    continue
                rule.fired += 1
                self._log.append((point, dict(ctx)))
                STATS.inc(f"fault.{point}")
                if buf is None:
                    buf = bytearray(data)
                if rule.offset is not None:
                    buf[rule.offset % len(buf)] ^= (rule.xor & 0xFF) or 0x01
                for _ in range(rule.bits):
                    pos = self._rand.randrange(len(buf) * 8)
                    buf[pos >> 3] ^= 1 << (pos & 7)
        return data if buf is None else bytes(buf)

    def shift(self, point: str, value, ctx: Dict[str, Any]):
        """Apply every armed delta= rule for `point` to a numeric
        result — a device fold that silently returns the wrong answer."""
        with self._mu:
            for rule in self._rules:
                if rule.point != point or rule.delta is None \
                        or not rule._matches(ctx):
                    continue
                rule.seen += 1
                if rule.seen <= rule.after:
                    continue
                if rule.times is not None and rule.fired >= rule.times:
                    continue
                if rule.prob < 1.0 and self._rand.random() >= rule.prob:
                    continue
                rule.fired += 1
                self._log.append((point, dict(ctx)))
                STATS.inc(f"fault.{point}")
                value = value + rule.delta
        return value


# Module-global active flag: point() must be near-free when nothing is
# armed — one global read, no lock, no registry walk.
_ACTIVE = False
_INJECTOR = Injector()
_ENV_LOADED = False


def _set_active(on: bool) -> None:
    global _ACTIVE
    _ACTIVE = on


def injector() -> Injector:
    return _INJECTOR


def arm(point: str, **kw) -> Rule:
    _load_env_once()
    return _INJECTOR.arm(point, **kw)


def disarm(rule: Rule) -> None:
    _INJECTOR.disarm(rule)


def reset(seed: Optional[int] = None) -> None:
    _INJECTOR.reset(seed)


def log() -> List[tuple]:
    return _INJECTOR.log()


def point(name: str, **ctx) -> None:
    """The injection seam. Near-free when nothing is armed."""
    if not _ACTIVE:
        if not _ENV_LOADED:
            _load_env_once()
            if not _ACTIVE:
                return
        else:
            return
    _INJECTOR.fire(name, ctx)


def corrupt(name: str, data: bytes, **ctx) -> bytes:
    """Bit-rot seam: returns `data` with armed bits=/offset= rules
    applied (identity when nothing is armed)."""
    if not _ACTIVE:
        if not _ENV_LOADED:
            _load_env_once()
            if not _ACTIVE:
                return data
        else:
            return data
    return _INJECTOR.mutate(name, data, ctx)


def perturb(name: str, value, **ctx):
    """Result-perturbation seam: returns `value` shifted by armed
    delta= rules (identity when nothing is armed)."""
    if not _ACTIVE:
        if not _ENV_LOADED:
            _load_env_once()
            if not _ACTIVE:
                return value
        else:
            return value
    return _INJECTOR.shift(name, value, ctx)


def active() -> bool:
    _load_env_once()
    return _ACTIVE


def _load_env_once() -> None:
    """Arm rules from PILOSA_TPU_FAULT exactly once per process (call
    load_env() to re-read after changing the env mid-process)."""
    global _ENV_LOADED
    if _ENV_LOADED:
        return
    _ENV_LOADED = True
    spec = os.environ.get("PILOSA_TPU_FAULT", "")
    if spec:
        load_spec(spec)


def load_env() -> None:
    """Force a re-read of PILOSA_TPU_FAULT (tests that set the env
    after import)."""
    global _ENV_LOADED
    _ENV_LOADED = False
    _load_env_once()


def load_spec(spec: str) -> List[Rule]:
    """Parse and arm a `point:key=val,...;point2:...` spec string."""
    from .config import parse_duration

    rules = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        pt, _, body = part.partition(":")
        kw: Dict[str, Any] = {"match": {}}
        for item in body.split(","):
            if not item:
                continue
            k, _, v = item.partition("=")
            k = k.strip()
            v = v.strip()
            if k == "error":
                if v not in _ERROR_NAMES:
                    raise ValueError(f"unknown fault error {v!r} "
                                     f"(want one of {sorted(_ERROR_NAMES)})")
                kw["error"] = _ERROR_NAMES[v]
            elif k == "delay":
                kw["delay"] = parse_duration(v)
            elif k == "times":
                kw["times"] = int(v)
            elif k == "after":
                kw["after"] = int(v)
            elif k == "prob":
                kw["prob"] = float(v)
            elif k in ("bits", "offset", "xor", "delta"):
                kw[k] = int(v, 0)
            else:
                kw["match"][k] = v
        rules.append(_INJECTOR.arm(pt.strip(), **kw))
    return rules
