"""Executor: recursive PQL evaluation fanned out per-slice.

Parity with /root/reference/executor.go: bitmap calls (Bitmap, Union,
Intersect, Difference, Range) map per-slice and merge; Count sums
per-slice counts; TopN is two-phase (approximate pass, then exact
re-count of the merged candidate ids); SetBit/ClearBit route to every
replica owner of the bit's slice; SetRowAttrs/SetColumnAttrs apply
locally and broadcast to all other nodes. A failed node's slices are
re-split across remaining replicas (executor.go:1140-1151).

The TPU twist: Count over a pure bitmap-op tree takes a fused device
path — the whole expression tree compiles to one XLA computation per
slice batch (pilosa_tpu.parallel.plan), popcounting the combined blocks
without materializing intermediate rows (closing the reference's
materialize-then-count gap, SURVEY.md §3.2 note).
"""

from __future__ import annotations

import os
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from datetime import datetime
from typing import Callable, List, Optional, Sequence

from .core import views_by_time_range
from .core.cache import add_to_pairs
from .core.fragment import TopOptions
from .core.frame import DEFAULT_ROW_LABEL
from .core.index import DEFAULT_COLUMN_LABEL
from .core.row import Row
from .core.view import VIEW_INVERSE, VIEW_STANDARD
from .errors import (
    BroadcastError,
    DeadlineExceededError,
    FrameNotFoundError,
    IndexNotFoundError,
    IndexRequiredError,
    QueryError,
    SliceUnavailableError,
    WriteConsistencyError,
)
from .parallel.cluster import (
    NODE_STATE_DOWN,
    NODE_STATE_UP,
    SERVING_STATES,
    pick_read_replica,
    preferred_owner,
)
from .parallel.epochs import EpochTracker, ResultCache, fragment_key
from .pql import Call, Query
from . import SLICE_WIDTH
from . import fault
from . import obs

# Frame used when a query doesn't specify one (executor.go:35).
DEFAULT_FRAME = "general"

# Lowest count a TopN pass will consider (executor.go:37-39).
MIN_THRESHOLD = 1

# PQL timestamp format (reference TimeFormat "2006-01-02T15:04").
TIME_FORMAT = "%Y-%m-%dT%H:%M"

_WRITE_CALLS = ("ClearBit", "SetBit", "SetValue", "SetRowAttrs",
                "SetColumnAttrs")

# BSI aggregates over integer fields (bsi.<field> views).
_BSI_AGGREGATES = ("Sum", "Min", "Max")

# Shadow-verification counters, keyed "checks:<backend>" /
# "mismatch:<backend>" — exported as pilosa_shadow_checks_total /
# pilosa_shadow_mismatch_total{backend} Prometheus families. A
# mismatch means the device returned a DIFFERENT answer than the host
# roaring fold for the same tree: miscompiled plan, bad staging, or
# silent device fault — the one failure class checksums can't see.
SHADOW_STATS = obs.StatMap()

# Write-consistency outcome counters, keyed "<level>:<outcome>" —
# exported as pilosa_write_consistency_total{level,outcome}. Outcomes:
# ok (all replicas acked), hinted (consistency reached, misses
# journaled as hints), below_consistency (dispatched but too few acks
# — 503 after hints enqueued), rejected_unavailable (too few owners
# reachable, rejected BEFORE local apply).
CONSISTENCY_STATS = obs.StatMap()


def _call_shape(c) -> str:
    """Structural fingerprint of a Call tree — names + frame args,
    row/column ids elided: `Count(Intersect(Bitmap[f],Bitmap[f]))`.
    The flight recorder's shape key (human-readable, bounded
    cardinality — one entry per query SHAPE, not per query)."""
    frame = c.args.get("frame")
    label = f"{c.name}[{frame}]" if isinstance(frame, str) else c.name
    if c.children:
        return (label + "("
                + ",".join(_call_shape(k) for k in c.children) + ")")
    return label


def required_acks(level: str, owners: int) -> int:
    """Replica acks (local apply included) a write needs before it is
    acked to the client."""
    if level == "one":
        return 1
    if level == "all":
        return owners
    return owners // 2 + 1  # quorum


class ExecOptions:
    """Per-Execute context (executor.go:1253-1256).

    `deadline` — absolute time.monotonic() instant by which the whole
    query must finish; every remote hop is given only the REMAINING
    budget and expiry raises DeadlineExceededError instead of riding
    out the flat per-hop client timeout. None = no deadline.
    `partial` — opt-in graceful degradation: a slice with no reachable
    owner is skipped and collected in `missing_slices` instead of
    failing the query with SliceUnavailableError."""

    def __init__(self, remote: bool = False,
                 deadline: Optional[float] = None, partial: bool = False,
                 staleness: float = 0.0):
        self.remote = remote
        self.deadline = deadline
        self.partial = partial
        # Bounded-staleness read budget in seconds (X-Pilosa-Staleness
        # / [cluster] default-read-staleness): > 0 lets the placement
        # layer spread eligible slices over in-sync replicas and the
        # coordinator serve from the epoch-keyed result cache. 0 (the
        # default) is a STRICT read — owner-only placement, no result
        # cache — bit-for-bit the pre-ISSUE-18 path.
        self.staleness = max(0.0, float(staleness))
        # Breaker states snapshotted ONCE per query (satellite of
        # ISSUE 18): every placement decision in this execution — the
        # initial split and any failure re-split — sees the same
        # breaker world, so a breaker flapping half-open mid-query
        # can't flip the pick between legs. None until execute() fills
        # it (or the client has no registry).
        self.breaker_snapshot: Optional[dict] = None
        # Slices this query could not serve (partial mode only); the
        # handler surfaces them as {partial: true, missing_slices}.
        self.missing_slices: List[int] = []
        # Locality-tier footprints, set while the query executes:
        # used_http when any slice group was actually submitted over
        # the HTTP ring (_mapper's remote leg), used_ici when slices
        # owned by a same-pod ICI peer were folded into the local mesh
        # dispatch (_slices_by_node). _record_route derives the
        # query's `tier` label (http > ici > local) from these.
        self.used_http = False
        self.used_ici = False

    def deadline_left(self) -> Optional[float]:
        """Remaining budget in seconds (negative when expired), or
        None when no deadline is set."""
        if self.deadline is None:
            return None
        return self.deadline - time.monotonic()

    def check_deadline(self, what: str = "query") -> None:
        left = self.deadline_left()
        if left is not None and left <= 0:
            raise DeadlineExceededError(
                f"{what}: deadline exceeded by {-left * 1e6:.0f}us")


def parse_time(s: str) -> datetime:
    return datetime.strptime(s, TIME_FORMAT)


def _device_top_pairs(frag, min_threshold: int, n: int):
    """Exact top-n (rowID, count) pairs, ordered (count desc, row asc),
    from a fragment's device pool image — or None when any part of the
    device attempt fails (caller serves the host path instead)."""
    import numpy as np

    from .ops.pool import pool_row_counts

    try:
        pool, row_ids = frag.pool
        if len(row_ids) == 0:
            return []
        # num_rows is a static jit arg: pad to the next power of two so
        # growing fragments recompile on doubling, not on every new row
        # (matching the pool's own capacity padding, ops/pool.py).
        padded = 1 << (len(row_ids) - 1).bit_length()
        counts = np.asarray(pool_row_counts(pool, padded))[:len(row_ids)]
    except Exception:  # noqa: BLE001 — device attempt failed: host path
        return None
    keep = np.nonzero(counts >= min_threshold)[0]
    order = np.lexsort((row_ids[keep], -counts[keep]))
    if n:
        order = order[:n]
    keep = keep[order]
    return [(int(row_ids[i]), int(counts[i])) for i in keep]


def needs_slices(calls: Sequence[Call]) -> bool:
    """True when any call requires per-slice fan-out (executor.go:1281)."""
    return any(c.name not in _WRITE_CALLS for c in calls)


class Executor:
    """Evaluates PQL against a Holder, fanning out across the cluster.

    `client` is the remote-execution seam (reference Executor.HTTPClient
    + exec, executor.go:1000-1083): any object with
    execute_query(node, index, query: str, slices, remote=True) -> list.
    Tests inject fakes here; the HTTP layer injects the real client.
    """

    def __init__(self, holder, host: str = "", cluster=None, client=None,
                 use_device: Optional[bool] = None, max_workers: int = 8,
                 device_min_work: Optional[int] = None,
                 prefer_local_reads: bool = False,
                 mesh_config: Optional[dict] = None,
                 ici_hosts: Optional[Sequence[str]] = None):
        self.holder = holder
        # [mesh] knobs (config.Config.mesh_config()) handed to the
        # MeshManager on construction: HBM budget, headroom, plan
        # quarantine policy. Empty dict = env/auto resolution.
        self.mesh_config = dict(mesh_config or {})
        self.host = host
        self.cluster = cluster
        self.client = client
        # Locality tie-break for slice placement: when on, a healthy
        # locally-held replica serves locally instead of paying the
        # HTTP hop to the ring-order primary. Off by default — the
        # reference routes each slice to ring order, spreading load
        # across replicas, which is right when clients hit every node.
        self.prefer_local_reads = prefer_local_reads
        # Same-pod ICI peers ([cluster] ici-hosts): hosts whose chips
        # share this node's interconnect AND whose data dirs are
        # replicated here (the SPMD deployment shape). Slices the ring
        # assigns to an ICI peer are served from the LOCAL mesh — the
        # collective already spans the pod's devices — so the query
        # pays one psum over the fabric instead of an HTTP leg
        # (_slices_by_node). The local host being listed is harmless.
        self.ici_hosts = frozenset(ici_hosts or ())
        # Write-path replication (ISSUE 13): replica acks required
        # before a mutation acks ("one" | "quorum" | "all"), and the
        # hinted-handoff manager that journals missed replica ops.
        # Both server-wired; a bare executor (unit tests) keeps the
        # legacy fail-on-remote-error behavior while `hints` is None.
        self.write_consistency: str = "quorum"
        self.hints = None
        # Liveness-plane read steering (ISSUE 20): server-wired to
        # HEALTH.peer_ready so follower reads route around a peer
        # whose gossiped health digest says a critical subsystem is
        # stalled. None = no filtering (bare executors, unit tests).
        self.peer_health_ok = None
        # None = auto (device path when available); False = host roaring only.
        self.use_device = use_device
        # Cost-routing threshold (see _route_to_host); None = resolve
        # from PILOSA_TPU_DEVICE_MIN_WORK / the use_device mode.
        self.device_min_work = device_min_work
        self._pool = ThreadPoolExecutor(max_workers=max_workers)
        # Separate pool for per-slice fan-out: _mapper submits node-level
        # tasks to _pool that block on slice-level results, so sharing
        # one bounded pool could deadlock with every worker waiting.
        self._slice_pool = ThreadPoolExecutor(max_workers=max_workers)
        # Mesh serving layer (parallel/serve.py): created on first use
        # when the device backend is on. Count/TopN slice batches route
        # through it as ONE shard_map'd collective; the per-slice paths
        # below remain the fallback.
        self._mesh_mgr = None
        self._mesh_mgr_failed = False
        # SPMD descriptor plane (parallel/spmd.py), set by server wiring
        # when [cluster] type = "spmd": device collectives must be
        # driven through the multi-host descriptor stream, never by
        # this process alone (a unilateral psum over a global mesh
        # hangs every rank).
        self._spmd = None
        # Guards lazy construction: two concurrent first queries must
        # not each build a manager and stage duplicate device images.
        import threading

        self._mesh_mgr_lock = threading.Lock()
        # Generation-validated caches for the cost-routed host count
        # path (plan.HostQueryCache): repeated small queries serve at
        # memo speed instead of re-extracting + re-folding.
        from .parallel.plan import HostQueryCache

        self._host_cache = HostQueryCache()
        # _route_to_host threshold, resolved once (the env lookup is
        # per-query overhead on the small-query path otherwise).
        self._min_work_resolved: Optional[int] = None
        # Backend-aware routing verdict (cpu backend + live native
        # kernels => large folds go to the host C++ path), resolved
        # once — jax.default_backend() and the ctypes load don't change
        # within a process.
        self._cpu_route_native: Optional[bool] = None
        # Route-level Count telemetry: which engine served (memo /
        # host-fold / mesh / roaring) and the end-to-end latency per
        # engine — the backend-labeled latency histogram at /metrics.
        self.route_stats = obs.StatMap()
        # Locality-tier split of the same routes, keyed "route|tier"
        # (tier ∈ local|ici|http): which interconnect the query's
        # slice fan-out actually crossed. Separate map so count_*
        # consumers keep exact keys.
        self.tier_stats = obs.StatMap()
        self._route_hists: dict = {}
        # Query-shape flight recorder (/debug/queryshapes): per
        # plan-signature route/tier/latency aggregation in a bounded
        # ring. The server resizes it from [obs] queryshape-ring.
        self.flight = obs.flight.FlightRecorder()
        # [integrity] shadow-sample-1-in: every Nth device Count/TopN
        # result is recomputed through the host roaring fold and
        # compared (0 = off). itertools.count() next() is atomic under
        # the GIL, so the sampler needs no lock.
        import itertools

        self.shadow_sample = 0
        self._shadow_counter = itertools.count()
        # Read-path resilience plane (ISSUE 18): the replication-epoch
        # tracker (what this coordinator knows about every replica's
        # write progress) and the epoch-keyed whole-query result cache
        # serving bounded-staleness repeats. Both live even on bare
        # executors — they are cheap dicts — and the server wires
        # their knobs ([cluster] result-cache-size, [integrity]
        # result-cache-verify-1-in).
        self.epochs = EpochTracker()
        self.result_cache = ResultCache()
        # Every Nth result-cache hit is recomputed and compared (the
        # PR-10 shadow-verify discipline): a mismatch means an entry
        # survived an epoch bump it should not have. 0 = off.
        self.result_cache_verify_1_in = 16
        self._rc_verify_counter = itertools.count(1)
        # Read-replica pick counters, keyed "pick|staleness_class"
        # (pick ∈ owner|follower|fallback_owner, class ∈
        # strict|bounded) -> pilosa_read_replica_total{replica,
        # staleness} at /metrics.
        self.read_stats = obs.StatMap()

    def set_spmd(self, spmd):
        """Wire the SPMD descriptor plane (rank 0 of a multi-host
        deployment): Count/TopN collectives and bit writes route
        through `spmd`, and the executor shares its MeshManager so
        staging/stats have one home."""
        self._spmd = spmd
        self._mesh_mgr = spmd.manager

    # Set True on SPMD worker ranks (server wiring): a mutation applied
    # here alone would silently diverge this rank's replica from the
    # descriptor-ordered stream — reject so the client retargets rank 0.
    spmd_reject_writes = False

    def _check_writable(self, what: str, opt: "ExecOptions"):
        if self.spmd_reject_writes and not opt.remote:
            raise QueryError(
                f"{what} must be sent to SPMD rank 0 (this is a worker "
                "rank; writes ride the descriptor stream)")

    # -- top level -----------------------------------------------------------

    def execute(self, index: str, q: Query, slices: Optional[Sequence[int]] = None,
                opt: Optional[ExecOptions] = None) -> list:
        """Execute each call serially, returning one result per call
        (executor.go:62-145)."""
        if not index:
            raise IndexRequiredError()
        # Slice-cover derivation is planning work: the max_slice scan
        # is the measurable part of query setup at headline slice
        # counts, so the plan phase brackets it (union-interval merges
        # with the per-call plan bracket in _execute_count).
        with obs.profile.phase("plan"):
            opt = opt or ExecOptions()

            # Snapshot breaker states once per query: placement (the
            # initial split AND any failure re-split) must not re-read
            # a registry a half-open probe is flapping mid-execution.
            if opt.breaker_snapshot is None:
                state = getattr(self.client, "breaker_state", None)
                if callable(state) and self.cluster is not None:
                    opt.breaker_snapshot = {
                        n.host: state(n.host)
                        for n in self.cluster.nodes}

            need = needs_slices(q.calls)
            # Built lazily on the first inverse call: most queries
            # touch no inverse view, and at headline slice counts (960)
            # the eager list was a measurable per-query tax on the
            # routed fast path.
            inverse_slices: Optional[List[int]] = None
            column_label = DEFAULT_COLUMN_LABEL

            idx = self.holder.index(index)
            defaulted = False
            if slices:
                slices = list(slices)
            else:
                slices = []
                if need:
                    if idx is None:
                        raise IndexNotFoundError()
                    defaulted = True
                    slices = list(range(idx.max_slice() + 1))
                    column_label = idx.column_label

        # Bulk attribute insertion fast path (executor.go:857-941).
        if q.calls and all(c.name == "SetRowAttrs" for c in q.calls):
            return self._execute_bulk_set_row_attrs(index, q.calls, opt)

        results = []
        for call in q.calls:
            opt.check_deadline(call.name)
            call_slices = slices
            if call.supports_inverse() and need:
                frame = call.args.get("frame") or DEFAULT_FRAME
                f = self.holder.frame(index, frame)
                if f is None:
                    raise FrameNotFoundError()
                if call.is_inverse(f.row_label, column_label):
                    if inverse_slices is None:
                        # Explicit caller slices keep their original
                        # behavior (inverse calls got the empty list);
                        # only the defaulted path derives the cover.
                        inverse_slices = list(
                            range(idx.max_inverse_slice() + 1)) \
                            if defaulted else []
                    call_slices = inverse_slices
            results.append(self._execute_call(index, call, call_slices, opt))
        return results

    def _execute_call(self, index: str, c: Call, slices: Sequence[int],
                      opt: ExecOptions):
        if c.name == "ClearBit":
            return self._execute_clear_bit(index, c, opt)
        if c.name == "Count":
            return self._execute_count(index, c, slices, opt)
        if c.name == "SetBit":
            return self._execute_set_bit(index, c, opt)
        if c.name == "SetValue":
            return self._execute_set_value(index, c, opt)
        if c.name in _BSI_AGGREGATES:
            return self._execute_bsi_aggregate(index, c, slices, opt)
        if c.name == "SetRowAttrs":
            return self._execute_set_row_attrs(index, c, opt)
        if c.name == "SetColumnAttrs":
            return self._execute_set_column_attrs(index, c, opt)
        if c.name == "TopN":
            return self._execute_top_n(index, c, slices, opt)
        return self._execute_bitmap_call(index, c, slices, opt)

    # -- bitmap calls --------------------------------------------------------

    def _execute_bitmap_call(self, index: str, c: Call, slices: Sequence[int],
                             opt: ExecOptions) -> Row:
        # Fused materialization (VERDICT r4 #5): a lowerable multi-leaf
        # tree folds dense word blocks once per slice and lifts the
        # RESULT into roaring — no per-operand container
        # materialization, no pairwise merges. Single-leaf Bitmap()
        # stays on the fragment row cache (a plain cache hit beats any
        # fold); non-lowerable trees keep the general roaring path.
        mat_plan = None
        if c.name in ("Intersect", "Union", "Difference", "Range"):
            from .parallel.plan import HostMaterializePlan, _lower_tree

            leaves: list = []
            shape = _lower_tree(self.holder, index, c, leaves)
            if shape is not None and len(leaves) > 1:
                mat_plan = HostMaterializePlan(
                    self.holder, index, shape, leaves,
                    cache=self._host_cache)

        if mat_plan is not None:
            def batch_fn(batch_slices):
                return mat_plan.materialize_row(batch_slices)

            def map_fn(slice_):
                seg = mat_plan.materialize_slice(slice_)
                r = Row()
                if seg is not None:
                    r.segments[slice_] = seg
                return r

            def reduce_fn(prev, v):
                # batch_fn/map_fn results are freshly built (never the
                # fragment row cache's shared Rows) — the first one can
                # be adopted without a defensive merge-clone.
                if prev is None:
                    return v
                prev.merge(v)
                return prev

            row = self._map_reduce(index, slices, c, opt, map_fn,
                                   reduce_fn, batch_fn=batch_fn)
        else:
            def map_fn(slice_):
                return self.execute_bitmap_call_slice(index, c, slice_)

            def reduce_fn(prev, v):
                if prev is None:
                    prev = Row()
                prev.merge(v)
                return prev

            row = self._map_reduce(index, slices, c, opt, map_fn, reduce_fn)
        if row is None:
            row = Row()

        # Attach attrs for root Bitmap() calls (executor.go:218-247).
        if c.name == "Bitmap":
            idx = self.holder.index(index)
            if idx is not None:
                col_id, col_ok = c.uint_arg(idx.column_label)
                if col_ok:
                    row.attrs = idx.column_attr_store.attrs(col_id)
                else:
                    f = idx.frame(c.args.get("frame") or DEFAULT_FRAME)
                    if f is not None:
                        row_id, _ = c.uint_arg(f.row_label)
                        row.attrs = f.row_attr_store.attrs(row_id)
        return row

    def execute_bitmap_call_slice(self, index: str, c: Call, slice_: int) -> Row:
        """One slice of a bitmap call (executor.go:253-268)."""
        if c.name == "Bitmap":
            return self._execute_bitmap_slice(index, c, slice_)
        if c.name == "Difference":
            return self._execute_binop_slice(index, c, slice_, "difference")
        if c.name == "Intersect":
            return self._execute_binop_slice(index, c, slice_, "intersect")
        if c.name == "Range":
            return self._execute_range_slice(index, c, slice_)
        if c.name == "Union":
            return self._execute_binop_slice(index, c, slice_, "union")
        raise QueryError(f"unknown call: {c.name}")

    def _execute_bitmap_slice(self, index: str, c: Call, slice_: int) -> Row:
        """Bitmap(rowID=..) / Bitmap(columnID=..) for one slice
        (executor.go:420-465)."""
        idx = self.holder.index(index)
        if idx is None:
            raise IndexNotFoundError()
        column_label = idx.column_label

        frame = c.args.get("frame") or DEFAULT_FRAME
        f = idx.frame(frame)
        if f is None:
            raise FrameNotFoundError()
        row_label = f.row_label

        row_id, row_ok = c.uint_arg(row_label)
        col_id, col_ok = c.uint_arg(column_label)
        if row_ok and col_ok:
            raise QueryError(
                f"Bitmap() cannot specify both {row_label} and {column_label} values")
        if not row_ok and not col_ok:
            raise QueryError(
                f"Bitmap() must specify either {row_label} or {column_label} values")

        view, id_ = VIEW_STANDARD, row_id
        if col_ok:
            if not f.inverse_enabled:
                raise QueryError(
                    "Bitmap() cannot retrieve columns unless inverse storage enabled")
            view, id_ = VIEW_INVERSE, col_id

        frag = self.holder.fragment(index, frame, view, slice_)
        if frag is None:
            return Row()
        return frag.row(id_)

    def _execute_binop_slice(self, index: str, c: Call, slice_: int, op: str) -> Row:
        if not c.children:
            if op == "union":
                return Row()
            raise QueryError(f"empty {c.name} query is currently not supported")
        other = None
        for child in c.children:
            row = self.execute_bitmap_call_slice(index, child, slice_)
            other = row if other is None else getattr(other, op)(row)
        return other

    def _execute_range_slice(self, index: str, c: Call, slice_: int) -> Row:
        """Range(frame=.., <row>=.., start=.., end=..) over time-quantum
        views (executor.go:490-546)."""
        frame = c.args.get("frame") or DEFAULT_FRAME
        f = self.holder.frame(index, frame)
        if f is None:
            raise FrameNotFoundError()

        # Value comparison over an integer field: Range(frame=f, v >= 3)
        # — one O'Neil plane ladder over the field's bsi view. This is
        # the per-slice host form; lowerable trees never get here (the
        # fused materialize/count paths lower the same ladder).
        fname_cond = self._bsi_cond(c)
        if fname_cond is not None:
            from .bsi import host as bsi_host

            fname, cond = fname_cond
            schema = f.bsi_field(fname)
            if schema is None:
                from .bsi import FieldNotFoundError

                raise FieldNotFoundError(frame, fname)
            frag = self.holder.fragment(index, frame, schema.view, slice_)
            return bsi_host.range_row(frag, schema, cond.op, cond.value)

        row_id, _ = c.uint_arg(f.row_label)

        start = c.args.get("start")
        if not isinstance(start, str):
            raise QueryError("Range() start time required")
        end = c.args.get("end")
        if not isinstance(end, str):
            raise QueryError("Range() end time required")
        try:
            start_t = parse_time(start)
            end_t = parse_time(end)
        except ValueError:
            raise QueryError("cannot parse Range() time")

        q = f.time_quantum
        if not str(q):
            return Row()

        out = Row()
        for vname in views_by_time_range(VIEW_STANDARD, start_t, end_t, q):
            frag = self.holder.fragment(index, frame, vname, slice_)
            if frag is None:
                continue
            out = out.union(frag.row(row_id))
        return out

    # -- count ---------------------------------------------------------------

    def _execute_count(self, index: str, c: Call, slices: Sequence[int],
                       opt: ExecOptions) -> int:
        if len(c.children) == 0:
            raise QueryError("Count() requires an input bitmap")
        if len(c.children) > 1:
            raise QueryError("Count() only accepts a single bitmap input")
        child = c.children[0]
        t0 = time.monotonic()
        h2d0 = self._h2d_bytes()

        # Whole-query memo (the Range/nary routed-path answer to the
        # reference's rank cache): a repeated read-only Count on an
        # unmutated holder is one dict probe validated by the
        # process-wide MUTATION_EPOCH — skipping re-lowering, plan
        # construction, and the per-slice generation walk, which
        # together dwarf the actual fold on small routed queries.
        # Single-node only: with cluster fan-out, remote writes don't
        # bump the LOCAL epoch, so a hit could serve another node's
        # stale slices. (SPMD replicates writes to every rank's holder
        # via the descriptor stream, so its rank-0 executor — which
        # has no cluster nodes — still qualifies; so does the default
        # server's one-node static cluster, where every write IS local.)
        psp = obs.span("plan", call="Count", slices=len(slices))
        pph = obs.profile.phase("plan").start()
        qkey = qepoch = qsepoch = None
        nodes = self.cluster.nodes if self.cluster is not None else []
        if (not nodes
                or (len(nodes) == 1 and nodes[0].host == self.host)):
            ck = c.cache_key()
            if ck is not None:
                from .core.fragment import MUTATION_EPOCH

                qkey = (index, ck, tuple(slices))
                qepoch = MUTATION_EPOCH.n
                qsepoch = MUTATION_EPOCH.s
                hit = self._host_cache.query_get(qkey, qepoch, qsepoch)
                if hit is not None:
                    psp.tag(route="memo").finish()
                    pph.stop()
                    # A memo hit never leaves this process: tier from
                    # the options anyway (a remote leg's hit still
                    # belongs to the tier the query paid), never the
                    # bare legacy default.
                    self._record_route("memo", t0,
                                       tier=self._query_tier(opt, False),
                                       call=c)
                    return hit

        # Epoch-keyed result cache (ISSUE 18): the clustered
        # counterpart of the memo above. Serves BOUNDED reads only
        # (X-Pilosa-Staleness > 0) on a multi-node cluster — strict
        # reads bypass (counted), keeping their byte-identical
        # owner-only path — keyed by (plan signature, slices, max
        # fragment epoch over the touched slices), so any write this
        # coordinator has observed to a touched slice produces a
        # different key and the stale entry invalidates instead of
        # serving. Every Nth hit is recomputed and compared (shadow
        # verify) to prove epoch-freshness end to end.
        rc = self.result_cache
        rc_key = rc_epoch = rc_verify = None
        if (rc is not None and not opt.remote and nodes
                and len(nodes) > 1):
            rck = c.cache_key()
            if opt.staleness <= 0 or rck is None:
                rc.bypass()
            else:
                rc_key = (index, rck, tuple(slices))
                # Epoch read BEFORE the probe/compute (the memo's
                # discipline): a write racing the fold bumps the max,
                # so the entry stored below can never validate for a
                # post-write read.
                rc_epoch = self.epochs.max_epoch_slices(index, slices)
                cached = rc.get(rc_key, rc_epoch)
                if cached is not None:
                    v1 = self.result_cache_verify_1_in
                    if v1 and next(self._rc_verify_counter) % v1 == 0:
                        rc_verify = cached  # recompute + compare below
                    else:
                        psp.tag(route="result-cache").finish()
                        pph.stop()
                        self._record_route(
                            "result-cache", t0,
                            tier=self._query_tier(opt, False),
                            call=c, cache="hit")
                        return cached

        # Lower the tree ONCE; every count engine shares it. The
        # per-slice CountPlan is only built if the mesh batch declines
        # (it compiles per-slice jits the batch path never uses).
        # Cost routing (_route_to_host) may decline the device entirely:
        # the query then runs the fused HOST fold (HostCountPlan — C++
        # popcount over dense word blocks, no roaring materialization),
        # which beats the materializing Row path ~5x on small trees.
        lowered = None
        host_lowered = None
        qtoken = None
        backend_on = self._device_backend_on()
        if backend_on or qkey is not None:
            # Lowering is pure host work; with the backend off it still
            # runs when a memo entry will be stored, because the leaves
            # name exactly the fragments the revalidation token must
            # cover (a tokenless entry dies on every epoch bump).
            from .parallel.plan import _lower_tree, _tree_signature

            leaves: list = []
            shape = _lower_tree(self.holder, index, child, leaves)
            route_reason = None
            if shape is not None and leaves:
                if backend_on:
                    import json as _json

                    sig = _json.dumps(_tree_signature(shape))
                    route_reason = self._route_to_host(
                        len(slices), len(leaves), index=index,
                        leaves=leaves, sig=sig)
                    if route_reason:
                        host_lowered = (shape, leaves)
                    else:
                        lowered = (shape, leaves)
                if qkey is not None:
                    qtoken = self._query_token(index, leaves, slices)

        # Routing decision, recorded for trace attribution: which
        # engine serves, and which kill-switches steered it there.
        route = ("host-fold" if host_lowered is not None
                 else "mesh" if lowered is not None else "roaring")
        psp.tag(route=route, backend_on=backend_on,
                leaves=len(leaves) if backend_on or qkey is not None
                else 0)
        if host_lowered is not None and route_reason:
            psp.tag(route_reason=route_reason)
        switches = self._kill_switches()
        if switches:
            psp.tag(kill_switches=switches)
        psp.finish()
        pph.stop()

        plan_cell: list = []

        def slice_plan():
            if not plan_cell:
                from .parallel.plan import CountPlan, HostCountPlan

                if lowered is not None:
                    plan_cell.append(CountPlan(self.holder, index, *lowered))
                elif host_lowered is not None:
                    plan_cell.append(
                        HostCountPlan(self.holder, index, *host_lowered,
                                      cache=self._host_cache))
                else:
                    plan_cell.append(None)
            return plan_cell[0]

        def map_fn(slice_):
            plan = slice_plan()
            if plan is not None:
                n = plan.count_slice(slice_)
                if n is not None:
                    return n
            return self.execute_bitmap_call_slice(index, child, slice_).count()

        def reduce_fn(prev, v):
            return (prev or 0) + v

        if host_lowered is not None:
            # Cost-routed host queries serve whole slice batches inline
            # (plan.count_slices): the per-slice thread fan-out costs
            # more than the memo-backed folds it would parallelize.
            def host_batch_fn(batch_slices):
                plan = slice_plan()
                return plan.count_slices(batch_slices) if plan else None

            batch_fn = host_batch_fn
        else:
            batch_fn = self._mesh_count_batch(index, lowered)

        # Host routes (roaring fold or the fused host popcount) do all
        # their gather work on host threads: the whole map-reduce is
        # host_fold time. The mesh route instead accrues device_exec /
        # stage_h2d / compile inside the serving layer (union-interval
        # accounting absorbs HostCountPlan's own nested bracket).
        gph = (obs.profile.phase("host_fold") if lowered is None
               else obs.profile.NOOP_PHASE)
        with gph:
            result = self._map_reduce(
                index, slices, c, opt, map_fn, reduce_fn, batch_fn=batch_fn)
            n = int(result or 0)
            if qkey is not None:
                # Stored against the PRE-compute epoch (and PRE-compute
                # fragment generations): a write racing the fold bumped
                # them, so the entry can never validate — stale results
                # invalidate, they don't serve.
                self._host_cache.query_put(qkey, qepoch, n, qsepoch, qtoken)
        cache_tag = None
        if rc_verify is not None:
            # Shadow verify: the hit we withheld vs the fresh compute.
            # A mismatch is an epoch-freshness bug — count it where
            # the PR-10 machinery already alerts (pilosa_shadow_
            # mismatch_total) and quarantine the entry.
            cache_tag = "verify"
            SHADOW_STATS.inc("checks:result-cache")
            if int(rc_verify) != n:
                SHADOW_STATS.inc("mismatch:result-cache")
                rc.invalidate(rc_key)
        elif rc_key is not None:
            cache_tag = "miss"
            rc.put(rc_key, rc_epoch, n)
        self._record_route(route, t0,
                           tier=self._query_tier(opt, route == "mesh"),
                           call=c,
                           staged_bytes=max(0, self._h2d_bytes() - h2d0),
                           cache=cache_tag)
        return n

    # Above this fan-out, gathering (fragment, generation) pairs for
    # the memo token costs more than the occasional refold it saves;
    # tokenless entries still epoch-validate (the r4 behavior).
    _QUERY_TOKEN_MAX = 8192

    def _query_token(self, index: str, leaves, slices) -> Optional[tuple]:
        """((fragment, generation), ...) across every (slice, unique
        leaf view) this Count touches — the revalidation token for
        HostQueryCache.query_get. Read BEFORE the fold on purpose (see
        query_put). Absent fragments are simply skipped: a fragment
        appearing later bumps the structural epoch (View._open_fragment),
        which already invalidates the token."""
        uniq = list(dict.fromkeys((f, v) for f, v, _r, _q in leaves))
        if len(uniq) * len(slices) > self._QUERY_TOKEN_MAX:
            return None
        pairs = []
        holder = self.holder
        for s in slices:
            for frame, view in uniq:
                frag = holder.fragment(index, frame, view, s)
                if frag is not None:
                    pairs.append((frag, frag.generation))
        return tuple(pairs)

    # -- BSI aggregates ------------------------------------------------------

    @staticmethod
    def _bsi_cond(c: Call):
        """The call's single field comparison as (field, Cond), None
        when it has none; raises QueryError on more than one."""
        from .pql.ast import Cond

        found = [(k, v) for k, v in c.args.items() if isinstance(v, Cond)]
        if not found:
            return None
        if len(found) > 1:
            raise QueryError(
                f"{c.name}() accepts one field comparison, got "
                f"{len(found)}")
        return found[0]

    def _bsi_call_schema(self, index: str, c: Call):
        """Resolve (frame name, Frame, FieldSchema) for a BSI aggregate
        call; raises the NotFound errors the handler maps to 404."""
        idx = self.holder.index(index)
        if idx is None:
            raise IndexNotFoundError()
        frame = c.args.get("frame") or DEFAULT_FRAME
        f = idx.frame(frame)
        if f is None:
            raise FrameNotFoundError()
        field = c.args.get("field")
        if not isinstance(field, str) or not field:
            raise QueryError(f"{c.name}() field required")
        schema = f.bsi_field(field)
        if schema is None:
            from .bsi import FieldNotFoundError

            raise FieldNotFoundError(frame, field)
        return frame, f, schema

    @staticmethod
    def _valcount_pair(v):
        """Normalize a per-leg aggregate result — local (value, count)
        tuple or a remote leg's decoded {"value", "count"} dict — to a
        tuple; None stays None (an empty Min/Max leg)."""
        if v is None:
            return None
        if isinstance(v, dict):
            return int(v.get("value", 0)), int(v.get("count", 0))
        return v

    def _execute_bsi_aggregate(self, index: str, c: Call,
                               slices: Sequence[int], opt: ExecOptions):
        """Sum / Min / Max over an integer field, with an optional
        bitmap filter child.

        Device path (single-host mesh): Sum is one fused per-row-count
        collective over the whole bsi view — every magnitude plane, the
        existence row, and the sign row counted in a single masked
        popcount + segment-sum — plus a second sign-side pass that is
        SKIPPED when no negative values exist (the sign count is
        visible in the first pass); the 2^k weighting folds host-side
        in unbounded Python ints. Min/Max binary-search the magnitude
        planes MSB-down, each probe one fused tree-count collective.
        Both shadow-verify sampled batches against the host roaring
        fold and serve the HOST value on mismatch.

        SPMD deployments route the same collectives through the BSISUM
        / COUNT descriptors (parallel/spmd.py) so every rank enters
        them together — the pod-scale form of the same plan.

        Host path (fallback, cost-routed small queries, remote legs'
        per-slice work): exact roaring folds in bsi.host."""
        frame, _f, schema = self._bsi_call_schema(index, c)
        if len(c.children) > 1:
            raise QueryError(
                f"{c.name}() only accepts a single bitmap input")
        child = c.children[0] if c.children else None
        t0 = time.monotonic()
        h2d0 = self._h2d_bytes()

        # Lower the filter child once; a non-lowerable filter pins the
        # whole aggregate to the host path (its per-slice evaluation
        # needs host state anyway).
        filter_lowered = None
        device_ok = self._device_backend_on()
        if device_ok and child is not None:
            from .parallel.plan import _lower_tree

            fleaves: list = []
            fshape = _lower_tree(self.holder, index, child, fleaves)
            if fshape is None or not fleaves:
                device_ok = False
            else:
                filter_lowered = (fshape, fleaves)
        if device_ok and self._route_to_host(
                len(slices), schema.row_count, index=index):
            device_ok = False

        view = schema.view
        from .bsi import host as bsi_host

        def map_fn(slice_):
            frag = self.holder.fragment(index, frame, view, slice_)
            filter_row = (self.execute_bitmap_call_slice(index, child,
                                                         slice_)
                          if child is not None else None)
            if c.name == "Sum":
                return bsi_host.sum_slice(frag, schema, filter_row)
            if c.name == "Max":
                return bsi_host.max_slice(frag, schema, filter_row)
            return bsi_host.min_slice(frag, schema, filter_row)

        if c.name == "Sum":
            def reduce_fn(prev, v):
                v = self._valcount_pair(v)
                if v is None:
                    return prev
                if prev is None:
                    return v
                return prev[0] + v[0], prev[1] + v[1]
        else:
            maximize = c.name == "Max"

            def reduce_fn(prev, v):
                return bsi_host.reduce_extremes(
                    [prev, self._valcount_pair(v)], maximize)

        batch_fn = None
        shadow_out: list = []  # per-check mismatch flags (flight rec)
        if device_ok:
            inner = (self._bsi_sum_batch(index, frame, schema,
                                         filter_lowered)
                     if c.name == "Sum" else
                     self._bsi_extremum_batch(index, frame, schema,
                                              filter_lowered,
                                              c.name == "Max"))
            if inner is not None:
                def batch_fn(batch_slices):
                    v = inner(batch_slices)
                    if v is not None and self._shadow_sampled():
                        v = self._shadow_check_bsi(
                            c.name, index, batch_slices, v, map_fn,
                            reduce_fn, outcome=shadow_out)
                    return v
            else:
                device_ok = False

        out = self._map_reduce(index, slices, c, opt, map_fn, reduce_fn,
                               batch_fn=batch_fn)
        self._record_route("bsi-mesh" if device_ok else "bsi-host", t0,
                           tier=self._query_tier(opt, device_ok),
                           call=c,
                           staged_bytes=max(0, self._h2d_bytes() - h2d0),
                           shadow_checked=bool(shadow_out),
                           shadow_mismatch=any(shadow_out))
        if c.name == "Sum":
            s, n = out if out is not None else (0, 0)
            return {"value": int(s), "count": int(n)}
        if out is None:
            return None
        return {"value": int(out[0]), "count": int(out[1])}

    def _bsi_sum_batch(self, index: str, frame: str, schema,
                       filter_lowered):
        """batch_fn computing (sum, count) for a slice batch from the
        fused per-row-count collectives, or None when no manager. With
        the SPMD plane wired, the collectives ride BSISUM descriptors
        (every rank must enter the psum together); the host-side 2^k
        weighting below is identical either way."""
        mgr = self.mesh_manager()
        if mgr is None:
            return None
        from .bsi.field import ROW_SIGN
        from .ops.bsi import sum_from_plane_dicts

        view = schema.view

        def plane_counts(batch_slices, num, src):
            if self._spmd is not None:
                return self._spmd.bsi_sum(index, frame, view,
                                          batch_slices, num, src=src)
            return mgr.bsi_plane_counts(index, frame, view,
                                        batch_slices, num, src=src)

        def batch_fn(batch_slices):
            num = self._batch_num_slices(index, batch_slices)
            try:
                counts = plane_counts(batch_slices, num, filter_lowered)
                if counts is None:
                    return None
                neg: dict = {}
                if counts.get(ROW_SIGN, 0):
                    # Negative values present: second pass restricted
                    # to the sign row (AND the filter, when given).
                    sshape: list = ["leaf"]
                    sleaves = [(frame, view, ROW_SIGN, False)]
                    if filter_lowered is not None:
                        fshape, fleaves = filter_lowered
                        sshape = ["and", fshape, ["leaf"]]
                        sleaves = list(fleaves) + sleaves
                    neg = plane_counts(batch_slices, num,
                                       (sshape, sleaves))
                    if neg is None:
                        return None
            except Exception:  # noqa: BLE001 — device failure → host
                return None
            return sum_from_plane_dicts(counts, neg, schema.bit_depth)

        return batch_fn

    def _bsi_extremum_batch(self, index: str, frame: str, schema,
                            filter_lowered, maximize: bool):
        """batch_fn binary-searching the magnitude planes MSB-down for
        a slice batch — ~bit_depth fused tree-count collectives over
        growing candidate trees. Returns (value, count) or None (empty
        batch falls through to the host fold, which agrees)."""
        mgr = self.mesh_manager()
        if mgr is None:
            return None
        from .bsi import lower as L
        from .bsi.field import ROW_PLANE0

        view = schema.view

        def batch_fn(batch_slices):
            num = self._batch_num_slices(index, batch_slices)

            def count_tree(tree):
                leaves: list = []
                shape = L.to_shape(tree, frame, view, leaves)
                if filter_lowered is not None:
                    fshape, fleaves = filter_lowered
                    shape = ["and", shape, fshape]
                    leaves = leaves + list(fleaves)
                try:
                    # SPMD: each probe is one COUNT descriptor so all
                    # ranks enter the collective together.
                    n = (self._spmd.count(index, shape, leaves,
                                          batch_slices, num)
                         if self._spmd is not None else
                         mgr.count(index, shape, leaves, batch_slices,
                                   num))
                except Exception:  # noqa: BLE001 — device → host
                    return None
                return None if n is None else int(n)

            def search(cand, big_mag: bool):
                mag = 0
                for k in range(schema.bit_depth - 1, -1, -1):
                    p = L.leaf(ROW_PLANE0 + k)
                    inter = L.t_and(cand, p)
                    if big_mag:
                        n = count_tree(inter)
                        if n is None:
                            return None
                        if n:
                            cand, mag = inter, mag | (1 << k)
                    else:
                        rest = L.t_andnot(cand, p)
                        n = count_tree(rest)
                        if n is None:
                            return None
                        if n:
                            cand = rest
                        else:
                            cand, mag = inter, mag | (1 << k)
                n = count_tree(cand)
                if n is None:
                    return None
                return mag, n

            n_pos = count_tree(L.POS)
            if n_pos is None:
                return None
            n_neg = count_tree(L.NEG)
            if n_neg is None:
                return None
            first, second = ((n_pos, L.POS, 1), (n_neg, L.NEG, -1))
            if not maximize:
                first, second = second, first
            for n_side, base, sign in (first, second):
                if not n_side:
                    continue
                # max: positives hold the LARGEST magnitude, negatives
                # the smallest; min mirrors.
                big = (sign > 0) == maximize
                out = search(base, big_mag=big)
                if out is None:
                    return None
                return sign * out[0], out[1]
            return None  # no values in batch; host fold agrees

        return batch_fn

    def _shadow_check_bsi(self, name: str, index: str, batch_slices,
                          device_v, map_fn, reduce_fn, outcome=None):
        """Recompute a sampled device aggregate through the host
        roaring fold and compare. On mismatch: count it, log, and
        serve the HOST value — BSI collectives are keyed per staged
        view rather than one plan signature, so the counter and log
        line are the alarm (as with TopN)."""
        SHADOW_STATS.inc("checks:bsi")
        host_v = None
        for s in batch_slices:
            host_v = reduce_fn(host_v, map_fn(s))
        if name == "Sum" and host_v is None:
            host_v = (0, 0)
        if host_v == self._valcount_pair(device_v):
            if outcome is not None:
                outcome.append(False)
            return device_v
        SHADOW_STATS.inc("mismatch:bsi")
        if outcome is not None:
            outcome.append(True)
        cur = obs.current_span()
        trace = getattr(getattr(cur, "trace", None), "trace_id", "-")
        obs.get_logger("executor").error(
            "shadow verification MISMATCH (bsi %s): device=%s host=%s "
            "index=%s slices=%d trace=%s — serving host fold",
            name, device_v, host_v, index, len(batch_slices), trace)
        return host_v

    def mesh_manager(self):
        """The mesh serving layer, or None when the device backend is
        off or its construction failed (no devices, import error)."""
        if self._mesh_mgr is not None:
            return self._mesh_mgr
        if self._mesh_mgr_failed or not self._device_backend_on():
            return None
        with self._mesh_mgr_lock:
            if self._mesh_mgr is not None or self._mesh_mgr_failed:
                return self._mesh_mgr
            try:
                from .parallel.serve import MeshManager

                self._mesh_mgr = MeshManager(self.holder,
                                             config=self.mesh_config)
            except Exception:  # noqa: BLE001 — device layer unavailable
                self._mesh_mgr_failed = True
                return None
        return self._mesh_mgr

    def invalidate_device_index(self, index: Optional[str] = None):
        """Drop staged device images for an index (or all). Called by
        the API layer on index/frame deletion — the object-identity
        check in refresh() also catches this, but dropping eagerly
        frees device HBM immediately."""
        if self._mesh_mgr is not None:
            self._mesh_mgr.invalidate(index)

    @property
    def device_stats(self):
        """Mesh serving-layer counters for /debug/vars, or None when no
        manager has been built (never forces construction)."""
        return self._mesh_mgr.stats if self._mesh_mgr is not None else None

    @property
    def host_cache_stats(self):
        """Routed-host-path cache counters for /debug/vars."""
        return self._host_cache.stats

    def _query_tier(self, opt: Optional["ExecOptions"],
                    collective: bool) -> str:
        """Locality tier a served query actually paid, worst-first:
        `http` when any slice group went over the HTTP ring, `ici`
        when a multi-device collective ran (slices reduced over the
        interconnect — including ICI-peer slices folded into the local
        dispatch), else `local` (one chip, or pure host fold)."""
        if opt is not None and opt.used_http:
            return "http"
        if opt is not None and opt.used_ici:
            return "ici"
        if collective and self._multi_device():
            return "ici"
        return "local"

    def _multi_device(self) -> bool:
        """True when the serving mesh spans more than one device (its
        reductions cross the interconnect)."""
        if self._spmd is not None:
            return True
        mgr = self._mesh_mgr
        try:
            return bool(mgr is not None
                        and mgr.mesh.devices.size > 1)
        except Exception:  # noqa: BLE001 — no mesh constructed
            return False

    @staticmethod
    def _shape_sig(c) -> str:
        """Structural plan signature for the flight recorder: call
        names plus frame arguments, with row/column ids elided — two
        queries differing only in ids aggregate as one shape. Memoized
        on the Call (immutable after parse, like cache_key)."""
        sig = c.__dict__.get("_shape_sig")
        if sig is None:
            try:
                sig = _call_shape(c)
            except Exception:  # noqa: BLE001 — telemetry never raises
                sig = c.name
            c.__dict__["_shape_sig"] = sig
        return sig

    def _h2d_bytes(self) -> int:
        """Cumulative mesh H2D staging bytes (0 without a manager) —
        deltas attribute staging cost to the query that triggered it
        (approximate under concurrency; it is an attribution
        instrument, not an invoice)."""
        stats = self.device_stats
        return int(stats.get("h2d_bytes", 0)) if stats is not None else 0

    def _record_route(self, route: str, t0: float,
                      tier: Optional[str] = None, call=None,
                      staged_bytes: int = 0,
                      shadow_checked: bool = False,
                      shadow_mismatch: bool = False,
                      cache: Optional[str] = None):
        self.route_stats.inc(f"count_{route}")
        # Tier split rides a parallel StatMap (route|tier) so the
        # legacy count_* keys — bench dumps, tests, dashboards — keep
        # their meaning; /metrics joins both into
        # pilosa_query_route_total{backend, tier}.
        self.tier_stats.inc(f"{route}|{tier or 'local'}")
        h = self._route_hists.get(route)
        if h is None:
            # setdefault: two first-observers race benignly to one.
            h = self._route_hists.setdefault(route, obs.Histogram())
        lat_us = (time.monotonic() - t0) * 1e6
        # Exemplar: with a trace active, its id rides into the latency
        # bucket this observation lands in, so /metrics?exemplars=true
        # links a burning p99 straight to /debug/traces/<id>. No trace
        # = None = zero extra work in the histogram.
        cur = obs.current_span()
        trace = getattr(cur, "trace", None)
        h.observe(lat_us, exemplar=getattr(trace, "trace_id", None))
        if call is not None:
            sig = self._shape_sig(call)
            self.flight.record(sig, route,
                               tier or "local", lat_us,
                               staged_bytes=staged_bytes,
                               shadow_checked=shadow_checked,
                               shadow_mismatch=shadow_mismatch,
                               cache=cache,
                               example=lambda: str(call))
            # Cost observatory tap: stamps the shape on the ambient
            # attribution context (the handler bound the tenant),
            # meters staged bytes + op count into the (tenant, shape)
            # account, and feeds the baseline watch. One attribute
            # read when the ledger is off.
            obs.costs.observe_route(sig, route, tier or "local",
                                    lat_us, staged_bytes=staged_bytes,
                                    cache=cache)

    @property
    def route_latency_hists(self) -> dict:
        """route name -> Histogram of Count latencies (µs), for the
        /metrics backend-labeled histogram."""
        return dict(self._route_hists)

    def estimate_service_us(self):
        """Admission-control service-time estimate (sched/): p95 of
        the busiest measured route's Count latency, in µs. None until
        enough queries have been measured — the scheduler blends this
        with its own observed latencies and a configured floor, so an
        honest 'don't know yet' beats a guess here."""
        best = None
        best_n = 0
        for h in list(self._route_hists.values()):
            n = h.total
            if n > best_n:
                best, best_n = h, n
        if best is None or best_n < 4:
            return None
        return best.percentile(0.95)

    def burst_hint(self, n: int):
        """Scheduler cohort-release hint: n coalesced queries are about
        to arrive together, so the mesh batch loop should hold its
        drain window open for the whole group (serve.expect_burst).
        No-op before the manager exists — a hint must never force
        device construction."""
        mgr = self._mesh_mgr
        if mgr is not None and n > 1:
            mgr.expect_burst(n)

    @staticmethod
    def _kill_switches() -> list:
        """The routing kill-switch env vars currently set, for trace
        attribution and EXPLAIN output."""
        switches = []
        for env, name in (("PILOSA_TPU_USE_DEVICE", "use_device"),
                          ("PILOSA_TPU_DEVICE_MIN_WORK", "device_min_work"),
                          ("PILOSA_TPU_CPU_ROUTE_NATIVE",
                           "cpu_route_native")):
            if os.environ.get(env, ""):
                switches.append(f"{name}={os.environ[env]}")
        return switches

    # -- explain -------------------------------------------------------------

    def explain(self, index: str, q: Query,
                slices: Optional[Sequence[int]] = None,
                opt: Optional[ExecOptions] = None) -> dict:
        """The PLANNED execution of `q` as a JSON-able dict: per-call
        routing decision with its cost-model inputs, slice→owner
        placement (breaker-aware, exactly the picks _slices_by_node
        would make), cache peeks, and estimated staging bytes — WITHOUT
        dispatching device work or mutating executor state. Every probe
        is a peek: no LRU reorder, no stats bumps, no staging, no
        compiles, no manager construction. Serves `?explain=true` on
        POST /index/{index}/query."""
        if not index:
            raise IndexRequiredError()
        idx = self.holder.index(index)
        if slices:
            slices = list(slices)
        else:
            slices = []
            if needs_slices(q.calls):
                if idx is None:
                    raise IndexNotFoundError()
                slices = list(range(idx.max_slice() + 1))
        return {
            "index": index,
            "slices": len(slices),
            "calls": [self._explain_call(index, c, slices, opt)
                      for c in q.calls],
        }

    def _explain_call(self, index: str, c: Call, slices: Sequence[int],
                      opt: Optional[ExecOptions] = None) -> dict:
        import json as _json

        info: dict = {"call": c.name}
        if c.name in _WRITE_CALLS:
            info["route"] = "write"
            info["placement"] = self._explain_placement(index, slices,
                                                        opt)
            owners = (self.cluster.replica_n
                      if self.cluster is not None and self.cluster.nodes
                      else 1)
            info["consistency"] = {
                "level": self.write_consistency,
                "replicas": owners,
                "required_acks": required_acks(
                    self.write_consistency, owners),
                "hinted_handoff": self.hints is not None,
            }
            return info
        if c.name in _BSI_AGGREGATES:
            return self._explain_bsi_aggregate(index, c, slices, info,
                                               opt)
        if c.name != "Count" or len(c.children) != 1:
            # Non-Count reads run the per-slice roaring map-reduce.
            info["route"] = "roaring"
            cond = self._find_cond(c)
            if cond is not None:
                # Range(field <op> N): report the plane ladder the
                # comparison compiles to, and what it would stage.
                from .parallel.plan import _lower_tree

                leaves: list = []
                shape = _lower_tree(self.holder, index, c, leaves)
                if shape is not None and leaves:
                    info["bsi"] = {"field": cond[0],
                                   "cond": str(cond[1]),
                                   "planes": len(leaves)}
                    info["staging"] = self._explain_staging(
                        index, leaves, slices)
            info["placement"] = self._explain_placement(index, slices,
                                                        opt)
            return info

        child = c.children[0]
        backend_on = self._device_backend_on()
        from .parallel.plan import _lower_tree, _tree_signature

        leaves: list = []
        shape = _lower_tree(self.holder, index, child, leaves)
        lowerable = shape is not None and bool(leaves)
        cond = self._find_cond(child)
        if cond is not None and lowerable:
            info["bsi"] = {"field": cond[0], "cond": str(cond[1]),
                           "planes": len(leaves)}

        # Memo peek mirrors _execute_count's single-node gate.
        memo_hit = False
        nodes = self.cluster.nodes if self.cluster is not None else []
        single = (not nodes
                  or (len(nodes) == 1 and nodes[0].host == self.host))
        ck = c.cache_key()
        if single and ck is not None:
            from .core.fragment import MUTATION_EPOCH

            memo_hit = self._host_cache.query_peek(
                (index, ck, tuple(slices)), MUTATION_EPOCH.n)

        route_reason = None
        if memo_hit:
            route = "memo"
        elif lowerable and backend_on:
            sig = _json.dumps(_tree_signature(shape))
            route_reason = self._would_route_to_host(
                len(slices), len(leaves), index=index, leaves=leaves,
                sig=sig)
            route = "host-fold" if route_reason else "mesh"
        else:
            route = "roaring"
        info["route"] = route
        if route_reason:
            info["route_reason"] = route_reason
        info["cost_model"] = {
            "backend_on": backend_on,
            "lowerable": lowerable,
            "leaves": len(leaves),
            "work_units": len(slices) * max(1, len(leaves)),
            "min_work": self._min_work(),
            "cpu_native_routes": self._cpu_native_routes(),
        }
        info["kill_switches"] = self._kill_switches()
        info["memo_hit"] = memo_hit

        mgr = self._mesh_mgr  # peek only: never force construction
        plan_hit = quarantined = False
        if lowerable and mgr is not None:
            sig = _json.dumps(_tree_signature(shape))
            plan_hit = mgr._fused_plans.contains_sig(sig)
            quarantined = mgr.plan_quarantined(sig)
        info["plan_cache"] = {"checked": mgr is not None,
                              "hit": plan_hit,
                              "quarantined": quarantined}
        if lowerable and mgr is not None:
            info["device_format"] = self._explain_format(
                index, leaves, shape, mgr)
        if lowerable:
            info["staging"] = self._explain_staging(index, leaves, slices)
        info["placement"] = self._explain_placement(index, slices, opt)
        return info

    @classmethod
    def _find_cond(cls, c: Call):
        """First (field, Cond) pair anywhere in a call tree — the
        explain() marker that a query compiles plane ladders."""
        from .pql.ast import Cond

        for k, v in c.args.items():
            if isinstance(v, Cond):
                return k, v
        for child in c.children:
            found = cls._find_cond(child)
            if found is not None:
                return found
        return None

    def _explain_bsi_aggregate(self, index: str, c: Call,
                               slices: Sequence[int], info: dict,
                               opt: Optional[ExecOptions] = None) -> dict:
        """Planned execution of Sum/Min/Max: which engine serves it,
        the plane count behind the field, and what a device dispatch
        would stage (every row of the bsi view)."""
        from .bsi import FieldNotFoundError

        try:
            frame, _f, schema = self._bsi_call_schema(index, c)
        except (IndexNotFoundError, FrameNotFoundError,
                FieldNotFoundError, QueryError) as err:
            # explain() never dispatches: a bad call reports its error
            # instead of raising, so the rest of the plan still renders.
            info["route"] = "error"
            info["error"] = str(err) or type(err).__name__
            return info
        backend_on = self._device_backend_on()
        route_reason = None
        if backend_on:
            route_reason = self._would_route_to_host(
                len(slices), schema.row_count, index=index)
            route = "bsi-host" if route_reason else "bsi-mesh"
        else:
            route = "bsi-host"
        info["route"] = route
        if route_reason:
            info["route_reason"] = route_reason
        info["bsi"] = {"field": c.args.get("field"),
                       "planes": schema.bit_depth,
                       "rows": schema.row_count}
        info["cost_model"] = {
            "backend_on": backend_on,
            "leaves": schema.row_count,
            "work_units": len(slices) * schema.row_count,
            "min_work": self._min_work(),
            "cpu_native_routes": self._cpu_native_routes(),
        }
        leaves = [(frame, schema.view, r, False)
                  for r in range(schema.row_count)]
        info["staging"] = self._explain_staging(index, leaves, slices)
        info["placement"] = self._explain_placement(index, slices, opt)
        return info

    @staticmethod
    def _resident_format(sv) -> str:
        """A StagedView's container format as the EXPLAIN label:
        dense / sparse / mixed (per-slice split)."""
        fmts = getattr(sv, "slice_formats", None)
        if sv.sparse is None or fmts is None or not fmts.any():
            return "dense"
        if fmts.all() or not sv.keys_host.shape[1]:
            return "sparse"
        return "mixed"

    def _explain_format(self, index: str, leaves, shape, mgr) -> dict:
        """Which container format would serve this Count on-device:
        per-leaf resident format plus whether the tree shape fits the
        sparse slice-group dispatch (and which sparse kernel backend
        is calibrated, if any). Peek only — unstaged leaves report
        "unstaged"; the stager decides their format at dispatch."""
        from .parallel.plan import _tree_signature

        fmts = []
        for frame, view, _r, _q in leaves:
            sv = mgr._views.get((index, frame, view))
            fmts.append("unstaged" if sv is None
                        else self._resident_format(sv))
        out: dict = {"leaves": fmts}
        if any(f in ("sparse", "mixed") for f in fmts):
            kind = mgr._sparse_shape_kind(_tree_signature(shape))
            out["sparse_shape"] = kind or "unsupported"
            # Peek the cached calibration pick; never trigger one.
            out["sparse_backend"] = (mgr._sparse_backend_cached
                                     or "unresolved")
        return out

    def _sparse_threshold_peek(self) -> float:
        """The sparse-density threshold the stager would use, without
        forcing manager construction: live manager if one exists, else
        the same env-over-config resolution it would apply."""
        mgr = self._mesh_mgr
        if mgr is not None:
            return mgr._sparse_threshold()
        cfg = self.mesh_config.get("sparse_density_threshold")
        base = float(cfg) if cfg is not None else 0.05
        try:
            return float(os.environ.get(
                "PILOSA_TPU_SPARSE_DENSITY_THRESHOLD", base))
        except ValueError:
            return base

    def _explain_staging(self, index: str, leaves,
                         slices: Sequence[int]) -> dict:
        """Which of the Count's (frame, view) images are already
        resident on-device — and in which container format — plus a
        host-side byte estimate for the ones a dispatch would have to
        stage, priced at the format pick_slice_formats would make
        today (dense slices at packed-word cost, sparse slices at
        sorted-array cost). Loaded fragments estimate from live
        container stats (exactly what the dual-pool builders upload);
        lazily-opened ones fall back to storage file size and stay
        format-unknown — EXPLAIN never forces a parse."""
        import numpy as np

        from .ops.pool import CONTAINER_WORDS
        from .parallel.mesh import pick_slice_formats

        mgr = self._mesh_mgr
        threshold = self._sparse_threshold_peek()
        uniq = list(dict.fromkeys((f, v) for f, v, _r, _q in leaves))
        staged = unstaged = est = 0
        views: list = []
        for frame, view in uniq:
            sv = (mgr._views.get((index, frame, view))
                  if mgr is not None else None)
            if sv is not None:
                staged += 1
                views.append({"frame": frame, "view": view,
                              "resident": True,
                              "format": self._resident_format(sv)})
                continue
            unstaged += 1
            stats = np.zeros((len(slices), 3), dtype=np.int64)
            opaque = 0
            for j, s in enumerate(slices):
                frag = self.holder.fragment(index, frame, view, s)
                if frag is None:
                    continue
                with frag._mu:
                    if frag._pending_load:
                        try:
                            opaque += os.path.getsize(frag.path)
                        except OSError:
                            pass
                        continue
                    nc = len(frag.storage.keys)
                    if nc:
                        ns = [c.n for c in frag.storage.containers]
                        stats[j] = (nc, sum(ns), max(ns))
            sp = pick_slice_formats(stats, threshold).astype(bool)
            n_sparse = int(sp.sum())
            n_live = int((stats[:, 0] > 0).sum())
            # Dense slices upload packed words; sparse ones upload the
            # value arrays plus their key/cardinality table entries.
            vb = (int(stats[~sp, 0].sum()) * (CONTAINER_WORDS * 4 + 4)
                  + int(stats[sp, 1].sum()) * 2
                  + int(stats[sp, 0].sum()) * 8 + opaque)
            est += vb
            views.append({
                "frame": frame, "view": view, "resident": False,
                "format": ("mixed" if 0 < n_sparse < n_live
                           else "sparse" if n_sparse else "dense"),
                "sparse_slices": n_sparse,
                "estimated_h2d_bytes": vb,
            })
        return {"staged_views": staged, "unstaged_views": unstaged,
                "estimated_h2d_bytes": est,
                "sparse_density_threshold": threshold,
                "views": views}

    def _explain_placement(self, index: str, slices: Sequence[int],
                           opt: Optional[ExecOptions] = None) -> dict:
        """slice→owner picks as _slices_by_node would make them —
        breaker/liveness-aware, and follower-spread when the request
        carries a staleness bound — plus each host's current breaker
        state, the locality tier of each pick (same-chip → same-pod-
        ICI → cross-node-HTTP), and the per-device group sizes one
        local mesh dispatch would shard the local+ici slices into.
        Slice lists are sampled (first 16) so a 960-slice explain
        stays readable. The follower p2c sample is seeded per explain
        so the rendered picks are stable within one response."""
        import random as _random

        from .parallel.cluster import owner_tier

        if self.cluster is None or not self.cluster.nodes:
            out = {"mode": "local", "slices": len(slices),
                   "tier": "ici" if self._multi_device() else "local"}
            self._explain_device_groups(out, slices, len(slices))
            return out
        state = self._breaker_callable(opt)
        read_bound = (opt.staleness
                      if opt is not None and not opt.remote else 0.0)
        rnd = _random.Random(0)
        nodes = list(self.cluster.nodes)
        per_host: dict = {}
        unowned: list = []
        tiers = {"local": 0, "ici": 0, "http": 0}
        read = {"staleness_s": read_bound, "followers": 0,
                "fallback_owner": 0} if read_bound > 0 else None
        for slice_ in slices:
            owners = [o for o in self.cluster.fragment_nodes(index, slice_)
                      if o in nodes]
            if not owners:
                unowned.append(slice_)
                continue
            pick = None
            role = "owner"
            if read_bound > 0 and len(owners) > 1:
                pick = pick_read_replica(
                    owners, state,
                    staleness_ok=lambda h, s=slice_:
                        self.epochs.staleness_ok_slice(
                            h, index, s, read_bound),
                    queue_depth=self.epochs.queue_depth,
                    prefer=self.host,
                    ici_hosts=self.ici_hosts or None, rnd=rnd,
                    node_ok=self.peer_health_ok)
                if pick is not None and pick.host != owners[0].host:
                    role = "follower"
                    read["followers"] += 1
                elif pick is None:
                    role = "fallback_owner"
                    read["fallback_owner"] += 1
            if pick is None:
                pick = preferred_owner(
                    owners, state,
                    prefer=self.host if self.prefer_local_reads else None,
                    ici_hosts=self.ici_hosts or None)
            tier = owner_tier(pick.host, self.host, self.ici_hosts)
            tiers[tier] += 1
            ent = per_host.setdefault(pick.host,
                                      {"slices": 0, "sample": [],
                                       "tier": tier})
            ent["slices"] += 1
            if read is not None:
                ent.setdefault("roles", {})
                ent["roles"][role] = ent["roles"].get(role, 0) + 1
            if len(ent["sample"]) < 16:
                ent["sample"].append(slice_)
        out = {"mode": "cluster", "nodes": per_host, "tiers": tiers,
               "tier": ("http" if tiers["http"]
                        else "ici" if tiers["ici"] or (
                            tiers["local"] and self._multi_device())
                        else "local")}
        if read is not None:
            out["read"] = read
        self._explain_device_groups(out, slices,
                                    tiers["local"] + tiers["ici"])
        if unowned:
            out["unowned_count"] = len(unowned)
            out["unowned_sample"] = unowned[:16]
        breakers = getattr(self.client, "breakers", None)
        snap = getattr(breakers, "snapshot", None)
        if callable(snap):
            out["breakers"] = snap()
        return out

    def _explain_device_groups(self, out: dict, slices, eligible) -> None:
        """Attach the per-device slice-group sizes one local mesh
        dispatch would shard the locally-served (local + ici tier)
        slices into. Peek only: the resident manager's mesh when one
        exists, else the process device count — never forces manager
        construction."""
        if not eligible or not slices or not self._device_backend_on():
            return
        try:
            if self._mesh_mgr is not None:
                n_dev = int(self._mesh_mgr.mesh.devices.size)
            else:
                import jax

                n_dev = len(jax.devices())
            from .parallel.plan import device_slice_groups

            out["device_groups"] = device_slice_groups(
                slices, max(slices) + 1, n_dev)
            out["devices"] = n_dev
        except Exception:  # noqa: BLE001 — explain never raises for this
            pass

    def _batch_num_slices(self, index: str, batch_slices) -> int:
        idx = self.holder.index(index)
        top = max(batch_slices) if batch_slices else 0
        if idx is not None:
            top = max(top, idx.max_slice())
        return top + 1

    def _shadow_sampled(self) -> bool:
        """True on every Nth call when [integrity] shadow-sample-1-in
        is set (N > 0)."""
        n = self.shadow_sample
        return n > 0 and next(self._shadow_counter) % n == 0

    def _shadow_check_count(self, index: str, shape, leaves, batch_slices,
                            device_n: int, backend: str) -> int:
        """Recompute a sampled device Count through the host roaring
        fold and compare. On mismatch: count it, log the divergence,
        quarantine the plan signature (identical queries host-fold
        until the TTL expires — a miscompiled plan must not keep
        serving wrong answers), and return the HOST value, which is
        what the caller serves. The host fold is ground truth: it reads
        the same roaring containers the checksums protect."""
        from .parallel.plan import HostCountPlan, _tree_signature

        SHADOW_STATS.inc(f"checks:{backend}")
        host_n = HostCountPlan(self.holder, index, shape, leaves,
                               cache=self._host_cache
                               ).count_slices(batch_slices)
        if host_n is None or int(host_n) == int(device_n):
            return device_n
        import json as _json

        sig = _json.dumps(_tree_signature(shape))
        SHADOW_STATS.inc(f"mismatch:{backend}")
        cur = obs.current_span()
        trace = getattr(getattr(cur, "trace", None), "trace_id", "-")
        obs.get_logger("executor").error(
            "shadow verification MISMATCH (%s): device=%d host=%d "
            "index=%s slices=%d trace=%s — quarantining plan sig",
            backend, int(device_n), int(host_n), index,
            len(batch_slices), trace)
        mgr = self._mesh_mgr
        if mgr is not None:
            mgr.quarantine_plan(sig)
        return int(host_n)

    def _mesh_count_batch(self, index: str, lowered):
        """A batch_fn serving a whole slice set as one mesh collective,
        or None when the tree/backend doesn't qualify. `lowered` is the
        (shape, leaves) pair from plan._lower_tree."""
        if lowered is None:
            return None
        mgr = self.mesh_manager()
        if mgr is None:
            return None
        shape, leaves = lowered

        if self._spmd is not None:
            # Multi-host: the collective must be driven through the
            # descriptor stream so every rank enters it together.
            def batch_fn(batch_slices):
                try:
                    n = self._spmd.count(
                        index, shape, leaves, batch_slices,
                        self._batch_num_slices(index, batch_slices))
                except Exception:  # noqa: BLE001 — device failure → host
                    return None
                if n is not None and self._shadow_sampled():
                    n = self._shadow_check_count(
                        index, shape, leaves, batch_slices, n, "spmd")
                return n

            return batch_fn

        def batch_fn(batch_slices):
            try:
                n = mgr.count(index, shape, leaves, batch_slices,
                              self._batch_num_slices(index, batch_slices))
            except Exception:  # noqa: BLE001 — any device failure → host path
                return None
            if n is not None and self._shadow_sampled():
                n = self._shadow_check_count(
                    index, shape, leaves, batch_slices, n, "mesh")
            return n

        return batch_fn

    # Default cost-routing threshold, in work units (slices × tree
    # leaves). Measured on the r2 rig: the device pays a ~2 ms dispatch
    # floor per query while the host C++ kernels cost ~10 µs per
    # slice-leaf unit (960 slices × 2 leaves ≈ 18 ms host, 2.8 ms
    # device) — crossover ≈ 200 units. The reference has no such split:
    # its per-query cost is flat regardless of size
    # (executor.go:567-597); here small queries must not pay the floor
    # (r2 measured nary_* at 26-270× SLOWER than host without routing).
    _DEFAULT_MIN_WORK = 192

    def _route_to_host(self, num_slices: int, num_leaves: int,
                       index: Optional[str] = None, leaves=None,
                       sig: Optional[str] = None) -> Optional[str]:
        """Truthy (the routing reason) when a lowerable Count tree
        should serve from the host C++ kernels anyway — falsy (None)
        when the device path should run. Cost reasons ("min_work",
        "cpu_native"): estimated device benefit below threshold.
        Threshold resolution: explicit device_min_work arg >
        PILOSA_TPU_DEVICE_MIN_WORK env > _DEFAULT_MIN_WORK. The cost
        model applies in EVERY device mode — use_device picks which
        backends are available, not which engine a given query should
        pay for; 0 disables cost routing (every lowerable tree → mesh).
        Routed queries count in /debug/vars mesh stats (routed_host).

        RESILIENCE reasons apply even with cost routing disabled, when
        `index`/`leaves`/`sig` context is supplied: "quarantined" (the
        plan signature is serving a quarantine TTL after repeated
        device failures) and "hbm_infeasible" (a leaf's view alone
        overflows [mesh] hbm-budget-bytes — staging is known-doomed,
        skip straight to the host fold). These also bump the matching
        pilosa_device_fallback_total reason counter.

        The router is BACKEND-AWARE above the threshold: on a `cpu`
        JAX backend, large folds route to the host C++ kernels too —
        JAX-on-CPU loses ~2x to the repo's own popcnt fold at every
        size (BENCH r03-r05 cpu-fallback headlines; the Roaring papers'
        host popcnt path is the CPU winner, arXiv:1611.07612), so with
        no accelerator behind the mesh the dispatch floor buys nothing.
        PILOSA_TPU_CPU_ROUTE_NATIVE=off pins large folds to the mesh
        (measurement / regression escape hatch); thr <= 0 still
        disables all COST routing."""
        reason = self._would_route_to_host(num_slices, num_leaves,
                                           index=index, leaves=leaves,
                                           sig=sig)
        if not reason:
            return None
        mgr = self.mesh_manager()
        if mgr is not None:
            mgr.stats.inc("routed_host")
            if reason in ("quarantined", "hbm_infeasible"):
                mgr.stats.inc(f"fallback_{reason}")
        return reason

    def _min_work(self) -> int:
        """The resolved cost-routing threshold (see _route_to_host)."""
        thr = self.device_min_work
        if thr is None:
            thr = self._min_work_resolved
        if thr is None:
            import os

            env = os.environ.get("PILOSA_TPU_DEVICE_MIN_WORK", "")
            if env:
                try:
                    thr = int(env)
                except ValueError:
                    thr = None
            if thr is None:
                thr = self._DEFAULT_MIN_WORK
            self._min_work_resolved = thr
        return thr

    def _would_route_to_host(self, num_slices: int, num_leaves: int,
                             index: Optional[str] = None, leaves=None,
                             sig: Optional[str] = None) -> Optional[str]:
        """The pure routing decision (reason string or None) — no
        stats, no manager construction — shared by _route_to_host and
        explain(). Resilience gates consult the EXISTING mesh manager
        only: with no manager yet there is nothing staged, no
        quarantine history, and no resolved budget to gate on."""
        mgr = self._mesh_mgr
        if mgr is not None:
            if sig and mgr.plan_quarantined(sig):
                return "quarantined"
            if index is not None and leaves:
                try:
                    if mgr.stage_infeasible(index, leaves, num_slices):
                        return "hbm_infeasible"
                except Exception:  # noqa: BLE001 — peek must not kill
                    pass           # the query; _stage_once re-checks
        thr = self._min_work()
        if thr <= 0:
            return None
        if num_slices * max(1, num_leaves) < thr:
            return "min_work"
        if self._cpu_native_routes():
            return "cpu_native"
        return None

    def _cpu_native_routes(self) -> bool:
        """True when large folds should route to the host despite
        clearing the work threshold: cpu JAX backend + native C++
        kernels live + not opted out (see _route_to_host)."""
        verdict = self._cpu_route_native
        if verdict is None:
            import os

            import jax

            from .ops import native

            verdict = (
                os.environ.get("PILOSA_TPU_CPU_ROUTE_NATIVE", "on").lower()
                not in ("off", "0")
                and jax.default_backend() == "cpu"
                and native.has_native())
            self._cpu_route_native = verdict
        return verdict

    def _device_backend_on(self) -> bool:
        """use_device: True forces the device path, False forces host
        roaring, None = auto — the PILOSA_TPU_USE_DEVICE env var if set
        (on/off/auto etc., config.parse_use_device), else device when a
        TPU backend is live. An unparseable env value warns once and
        falls back to auto rather than failing every query."""
        if self.use_device is False:
            return False
        if self.use_device is None:
            import os

            from .config import parse_use_device

            try:
                forced = parse_use_device(
                    os.environ.get("PILOSA_TPU_USE_DEVICE", ""))
            except ValueError as e:
                if not getattr(self, "_warned_env", False):
                    self._warned_env = True
                    obs.get_logger("executor").warning(
                        "ignoring PILOSA_TPU_USE_DEVICE: %s", e)
                forced = None
            if forced is not None:
                return forced
            import jax

            return jax.default_backend() == "tpu"
        return True

    # -- TopN ----------------------------------------------------------------

    def _execute_top_n(self, index: str, c: Call, slices: Sequence[int],
                       opt: ExecOptions) -> List[tuple]:
        """Two-phase TopN (executor.go:273-310)."""
        row_ids, _ = c.uint_slice_arg("ids")
        n, _ = c.uint_arg("n")

        exact = [False]
        pairs = self._execute_top_n_slices(index, c, slices, opt, exact)
        if not pairs or row_ids or opt.remote:
            return pairs
        if exact[0]:
            # Phase 1 was served by the mesh path with exact global
            # counts — the reference needs phase 2 only because its
            # phase 1 is rank-cache-approximate; a recount would run
            # the identical collective again.
            return pairs

        # Phase 2: exact re-count of candidate ids, only at the coordinator.
        other = c.clone()
        other.args["ids"] = sorted(p[0] for p in pairs)
        trimmed = self._execute_top_n_slices(index, other, slices, opt)
        if n and n < len(trimmed):
            trimmed = trimmed[:n]
        return trimmed

    def _execute_top_n_slices(self, index: str, c: Call, slices: Sequence[int],
                              opt: ExecOptions,
                              exact: Optional[list] = None) -> List[tuple]:
        def map_fn(slice_):
            return self.execute_top_n_slice(index, c, slice_)

        def reduce_fn(prev, v):
            return add_to_pairs(prev or [], v)

        batch_fn = self._mesh_top_n_batch(index, c)
        single_node = self.cluster is None or not self.cluster.nodes
        if batch_fn is not None and exact is not None and single_node:
            inner = batch_fn

            def batch_fn(batch_slices):
                v = inner(batch_slices)
                if v is not None:
                    # Device counts cover every requested slice of the
                    # only node — already exact.
                    exact[0] = True
                return v

        pairs = self._map_reduce(index, slices, c, opt, map_fn, reduce_fn,
                                 batch_fn=batch_fn) or []
        pairs.sort(key=lambda p: (-p[1], p[0]))
        return pairs

    def _mesh_top_n_batch(self, index: str, c: Call):
        """A batch_fn serving TopN (and its exact ids phase 2) as
        masked row-count collectives — including a src bitmap child
        (evaluated on device, serve.row_counts_src), attr filters
        (exact device counts + a bounded host attr walk), and tanimoto
        (band math over three exact device vectors); None only for a
        non-lowerable src tree or malformed args (host path owns the
        error reporting)."""
        if not self._device_backend_on():
            # Must be checked BEFORE consulting the manager: an SPMD
            # worker rank has a manager injected for stats visibility
            # but use_device=False — letting it drive mgr.top_n would
            # enter a global-mesh psum unilaterally and hang every
            # rank.
            return None
        mgr = self.mesh_manager()
        if mgr is None:
            return None
        tanimoto, _ = c.uint_arg("tanimotoThreshold")
        if tanimoto > 100:
            return None  # host path owns the error
        attr_predicate = None
        filters = c.args.get("filters")
        field = c.args.get("field") or ""
        if filters and field:
            f_obj = self.holder.frame(index,
                                      c.args.get("frame") or DEFAULT_FRAME)
            if f_obj is None or f_obj.row_attr_store is None:
                return None
            store, allowed = f_obj.row_attr_store, set(filters)

            def attr_predicate(row_id):
                attr = store.attrs(row_id)
                return bool(attr) and attr.get(field) in allowed
        elif filters:
            return None  # filters without a field: host path owns errors
        src = None
        if tanimoto and not c.children:
            return None  # tanimoto requires a src bitmap
        if c.children:
            if len(c.children) > 1:
                return None
            from .parallel.plan import _lower_tree

            src_leaves: list = []
            src_shape = _lower_tree(self.holder, index, c.children[0],
                                    src_leaves)
            if src_shape is None or not src_leaves:
                return None
            src = (src_shape, src_leaves)
        frame = c.args.get("frame") or DEFAULT_FRAME
        n, _ = c.uint_arg("n")
        row_ids, _ = c.uint_slice_arg("ids")
        min_threshold, _ = c.uint_arg("threshold")

        # Shadow verification applies only to the exact-ids form: its
        # host recount (f.top over storage) is ground truth, where the
        # ranked form's host pass is cache-approximate and would
        # false-positive against exact device counts.
        shadow_ok = bool(row_ids) and src is None and \
            attr_predicate is None and not tanimoto

        def shadow(batch_slices, pairs, backend):
            if pairs is None or not shadow_ok or not self._shadow_sampled():
                return pairs
            return self._shadow_check_top_n(index, c, batch_slices,
                                            pairs, backend)

        if self._spmd is not None:
            def batch_fn(batch_slices):
                try:
                    pairs = self._spmd.top_n(
                        index, frame, VIEW_STANDARD, batch_slices,
                        self._batch_num_slices(index, batch_slices),
                        0 if row_ids else n, row_ids,
                        min_threshold or MIN_THRESHOLD, src=src,
                        attr_predicate=attr_predicate,
                        tanimoto_threshold=tanimoto)
                except Exception:  # noqa: BLE001 — device failure → host
                    return None
                return shadow(batch_slices, pairs, "spmd")

            return batch_fn

        def batch_fn(batch_slices):
            try:
                pairs = mgr.top_n(
                    index, frame, VIEW_STANDARD, batch_slices,
                    self._batch_num_slices(index, batch_slices),
                    0 if row_ids else n, row_ids,
                    min_threshold or MIN_THRESHOLD, src=src,
                    attr_predicate=attr_predicate,
                    tanimoto_threshold=tanimoto)
            except Exception:  # noqa: BLE001 — any device failure → host path
                return None
            return shadow(batch_slices, pairs, "mesh")

        return batch_fn

    def _shadow_check_top_n(self, index: str, c: Call, batch_slices,
                            pairs, backend: str):
        """Recompute a sampled exact-ids TopN through the host storage
        recount and compare. On mismatch the batch_fn returns None, so
        the map/reduce host path serves the query — TopN device
        programs are keyed per fragment pool rather than per query
        tree, so there is no plan signature to quarantine; the mismatch
        counter and log line are the alarm."""
        SHADOW_STATS.inc(f"checks:{backend}")
        host: List[tuple] = []
        for s in batch_slices:
            host = add_to_pairs(host, self.execute_top_n_slice(index, c, s))
        if dict(host) == dict(pairs):
            return pairs
        SHADOW_STATS.inc(f"mismatch:{backend}")
        cur = obs.current_span()
        trace = getattr(getattr(cur, "trace", None), "trace_id", "-")
        obs.get_logger("executor").error(
            "shadow verification MISMATCH (%s TopN): device=%s host=%s "
            "index=%s slices=%d trace=%s — serving host recount",
            backend, dict(pairs), dict(host), index, len(batch_slices),
            trace)
        return None

    def execute_top_n_slice(self, index: str, c: Call, slice_: int) -> List[tuple]:
        """One slice of TopN (executor.go:333-396)."""
        frame = c.args.get("frame") or DEFAULT_FRAME
        n, _ = c.uint_arg("n")
        field = c.args.get("field") or ""
        row_ids, _ = c.uint_slice_arg("ids")
        min_threshold, _ = c.uint_arg("threshold")
        filters = c.args.get("filters") or []
        tanimoto, _ = c.uint_arg("tanimotoThreshold")

        src = None
        if len(c.children) == 1:
            src = self.execute_bitmap_call_slice(index, c.children[0], slice_)
        elif len(c.children) > 1:
            raise QueryError("TopN() can only have one input bitmap")

        f = self.holder.fragment(index, frame, VIEW_STANDARD, slice_)
        if f is None:
            return []
        if min_threshold <= 0:
            min_threshold = MIN_THRESHOLD
        if tanimoto > 100:
            raise QueryError("Tanimoto Threshold is from 1 to 100 only")

        # Plain TopN (no src/ids/filters/tanimoto) evaluates on device:
        # one fused popcount + segment-sum over the fragment's HBM pool
        # (ops/pool.pool_row_counts). EXACT counts over every row — a
        # strict improvement on the reference's rank-cache approximation
        # pass (fragment.go:493-625); the args that need host state
        # (attr filters, src intersection) keep the host path.
        if (src is None and not row_ids and not filters and tanimoto == 0
                and self._device_backend_on()):
            pairs = _device_top_pairs(f, min_threshold, n)
            if pairs is not None:
                return pairs

        return f.top(TopOptions(
            n=n,
            src=src,
            row_ids=row_ids,
            min_threshold=min_threshold,
            filter_field=field,
            filter_values=filters,
            tanimoto_threshold=tanimoto,
        ))

    # -- writes --------------------------------------------------------------

    def _read_bit_args(self, index: str, c: Call):
        idx = self.holder.index(index)
        if idx is None:
            raise IndexNotFoundError()
        frame = c.args.get("frame")
        if not isinstance(frame, str):
            raise QueryError(f"{c.name}() frame required")
        f = idx.frame(frame)
        if f is None:
            raise FrameNotFoundError()

        row_id, ok = c.uint_arg(f.row_label)
        if not ok:
            raise QueryError(f"{c.name}() row field '{f.row_label}' required")
        col_id, ok = c.uint_arg(idx.column_label)
        if not ok:
            raise QueryError(f"{c.name}() column field '{idx.column_label}' required")
        return f, row_id, col_id

    def _execute_set_bit(self, index: str, c: Call, opt: ExecOptions) -> bool:
        self._check_writable("SetBit()", opt)
        f, row_id, col_id = self._read_bit_args(index, c)

        timestamp = None
        ts = c.args.get("timestamp")
        if isinstance(ts, str):
            try:
                timestamp = parse_time(ts)
            except ValueError:
                raise QueryError(f"invalid date: {ts}")

        if self._spmd is not None and not opt.remote:
            # Multi-host SPMD: the write broadcast on the descriptor
            # stream IS the replication (every rank applies it to its
            # holder, totally ordered with queries) — the per-replica
            # HTTP fan-out below is the single-host-cluster path.
            return self._spmd.write(index, f.name, row_id, col_id,
                                    ts if isinstance(ts, str) else None,
                                    clear=False)

        return self._execute_mutate_view(
            index, c, opt, col_id,
            lambda: f.set_bit(row_id, col_id, timestamp,
                              deadline=opt.deadline))

    def _execute_set_value(self, index: str, c: Call,
                           opt: ExecOptions) -> bool:
        """SetValue(frame=f, col=N, field=V): overwrite a column's
        integer field value. The encode covers EVERY row of the bsi
        view (set + clear lists), so overwrite needs no
        read-modify-write; replication rides the same quorum fan-out
        as SetBit — the call re-parses verbatim on replicas and hints."""
        self._check_writable("SetValue()", opt)
        idx = self.holder.index(index)
        if idx is None:
            raise IndexNotFoundError()
        frame = c.args.get("frame")
        if not isinstance(frame, str):
            raise QueryError("SetValue() frame required")
        f = idx.frame(frame)
        if f is None:
            raise FrameNotFoundError()
        col_id, ok = c.uint_arg(idx.column_label)
        if not ok:
            raise QueryError(
                f"SetValue() column field '{idx.column_label}' required")

        fields = [(k, v) for k, v in c.args.items()
                  if k not in ("frame", idx.column_label)]
        if len(fields) != 1:
            raise QueryError(
                "SetValue() requires exactly one field=value pair")
        fname, value = fields[0]
        if isinstance(value, bool) or not isinstance(value, int):
            raise QueryError(f"SetValue() field '{fname}' must be an int")
        schema = f.bsi_field(fname)
        if schema is None:
            from .bsi import FieldNotFoundError

            raise FieldNotFoundError(frame, fname)
        # Validate BEFORE any replica sees the write: an out-of-range
        # value is a clean 422 with no state mutated anywhere.
        schema.validate(value)

        if self._spmd is not None and not opt.remote:
            # The SPMD write descriptor encodes (row, col, clear) bit
            # flips only; multi-valued field writes don't fit it yet.
            raise QueryError(
                "SetValue() is not supported under SPMD serving")

        return self._execute_mutate_view(
            index, c, opt, col_id,
            lambda: f.set_value(fname, col_id, value,
                                deadline=opt.deadline))

    def _execute_clear_bit(self, index: str, c: Call, opt: ExecOptions) -> bool:
        self._check_writable("ClearBit()", opt)
        f, row_id, col_id = self._read_bit_args(index, c)
        if self._spmd is not None and not opt.remote:
            return self._spmd.write(index, f.name, row_id, col_id, None,
                                    clear=True)
        return self._execute_mutate_view(
            index, c, opt, col_id,
            lambda: f.clear_bit(row_id, col_id, deadline=opt.deadline))

    def _execute_mutate_view(self, index: str, c: Call, opt: ExecOptions,
                             col_id: int, local_fn: Callable[[], bool]) -> bool:
        """Route a bit mutation to every replica owner of its slice
        (executor.go:767-797), with quorum semantics instead of the
        reference's serial first-error-fails fan-out.

        Owners are dispatched in PARALLEL and every future is awaited
        (the _broadcast_query discipline). The write acks once
        `write-consistency` replicas — local apply included — succeed;
        misses are journaled as hints for the drainer to replay, so an
        acked write converges without waiting for anti-entropy. Two
        orderings are load-bearing: owners the failure detector already
        knows are down (node state DOWN, breaker open) are counted
        BEFORE local apply — a write that cannot possibly reach
        consistency is rejected with no state mutated anywhere, so
        there is no acked-but-ambiguous outcome and the write path
        never pays a timeout to a known-dead node; and hints are
        enqueued even on the below-consistency path, because any
        replica that DID apply must still converge with the rest."""
        slice_ = col_id // SLICE_WIDTH
        owners = self._fragment_nodes(index, slice_)
        locals_ = [n for n in owners if n is None or n.host == self.host]
        remotes = [n for n in owners if n is not None and n.host != self.host]

        if opt.remote or not remotes:
            # Remote leg (the coordinator counts this node's ack) or a
            # single-owner slice: plain local apply.
            ret = False
            for _ in locals_:
                if local_fn():
                    ret = True
            if locals_:
                self._observe_write_epochs(index, c, slice_)
            return ret

        level = self.write_consistency
        required = required_acks(level, len(owners))
        hints = self.hints

        down: list = []
        live = list(remotes)
        if hints is not None:
            breaker = self._breaker_callable()
            down = [n for n in remotes
                    if n.state == NODE_STATE_DOWN
                    or (breaker is not None
                        and breaker(n.host) == "open")]
            live = [n for n in remotes if n not in down]
            if len(locals_) + len(live) < required:
                CONSISTENCY_STATS.inc(f"{level}:rejected_unavailable")
                raise WriteConsistencyError(
                    f"write-consistency={level} needs {required} of "
                    f"{len(owners)} replicas, only "
                    f"{len(locals_) + len(live)} reachable",
                    level=level, required=required, acked=0)

        ret = False
        acked = 0
        for _ in locals_:
            if local_fn():
                ret = True
            acked += 1
        wrote_epochs: dict = {}
        if locals_:
            wrote_epochs = self._observe_write_epochs(index, c, slice_)

        q = Query(calls=[c])
        futures = [
            (node, self._pool.submit(obs.wrap_ctx(self._exec_remote),
                                     node, index, q, None, opt))
            for node in live
        ]
        failures = []
        for node, fut in futures:
            try:
                res = fut.result()
                if res and res[0]:
                    ret = True
                acked += 1
            except Exception as err:  # noqa: BLE001 — collected below
                failures.append((node.host, err))

        if hints is None:
            # Legacy contract for bare executors: no handoff plane
            # means no repair path, so a remote failure must surface.
            if failures:
                raise failures[0][1]
            return ret

        pql = str(q)
        missed = [n.host for n in down] + [h for h, _ in failures]
        for host in missed:
            hints.enqueue_query(host, index, pql, epochs=wrote_epochs)

        if acked >= required:
            CONSISTENCY_STATS.inc(
                f"{level}:hinted" if missed else f"{level}:ok")
            return ret
        CONSISTENCY_STATS.inc(f"{level}:below_consistency")
        raise WriteConsistencyError(
            f"write-consistency={level}: {acked} of {required} required "
            f"replica acks ({len(failures)} failed mid-write; misses "
            f"journaled as hints)",
            level=level, required=required, acked=acked)

    def _observe_write_epochs(self, index: str, c: Call,
                              slice_: int) -> dict:
        """Feed the epoch tracker the post-apply epochs of every
        fragment a local mutation touched (the write fans out to one
        frame, but a SetBit may land in standard + inverse + time
        views): the coordinator's freshness bar advances at WRITE
        time, not at the next digest poll, so a follower missing this
        write ages from now. Returns the observed (key -> epoch) map —
        the write path carries it on hints so replay can floor-raise
        the recovered replica to the origin's numbering."""
        out: dict = {}
        tracker = self.epochs
        if tracker is None:
            return out
        frame = c.args.get("frame")
        f = self.holder.frame(index, frame if isinstance(frame, str)
                              and frame else DEFAULT_FRAME)
        if f is None:
            return out
        for vname, view in list(f.views.items()):
            frag = view.fragments.get(slice_)
            if frag is not None and not frag._pending_load:
                key = fragment_key(index, f.name, vname, slice_)
                tracker.observe_local(key, frag.epoch)
                out[key] = frag.epoch
        return out

    def _fragment_nodes(self, index: str, slice_: int):
        if self.cluster is None or not self.cluster.nodes:
            return [None]  # single-node: always local
        return self.cluster.fragment_nodes(index, slice_)

    def _other_nodes(self):
        if self.cluster is None:
            return []
        return [n for n in self.cluster.nodes if n.host != self.host]

    def _execute_set_row_attrs(self, index: str, c: Call, opt: ExecOptions):
        """SetRowAttrs (executor.go:799-855)."""
        self._check_writable("SetRowAttrs()", opt)
        if self._spmd is not None and not opt.remote:
            # Replicate through the descriptor stream (PQL re-serialized,
            # the reference's own remote-exec encoding, pql/ast.go
            # String()): every rank applies the attrs to its own store,
            # totally ordered with writes and queries.
            return self._spmd.execute_pql(index, str(c))
        frame_name = c.args.get("frame")
        if not isinstance(frame_name, str):
            raise QueryError("SetRowAttrs() frame required")
        f = self.holder.frame(index, frame_name)
        if f is None:
            raise FrameNotFoundError()
        row_id, ok = c.uint_arg(f.row_label)
        if not ok:
            raise QueryError(f"SetRowAttrs() row field '{f.row_label}' required")

        attrs = dict(c.args)
        attrs.pop("frame", None)
        attrs.pop(f.row_label, None)
        f.row_attr_store.set_attrs(row_id, attrs)

        if not opt.remote:
            self._broadcast_with_hints(index, Query(calls=[c]), opt)
        return None

    def _execute_bulk_set_row_attrs(self, index: str, calls: Sequence[Call],
                                    opt: ExecOptions) -> list:
        """Grouped bulk insertion (executor.go:857-941)."""
        self._check_writable("SetRowAttrs()", opt)
        if self._spmd is not None and not opt.remote:
            self._spmd.execute_pql(index, " ".join(str(c) for c in calls))
            return [None] * len(calls)
        by_frame = {}
        for c in calls:
            frame_name = c.args.get("frame")
            if not isinstance(frame_name, str):
                raise QueryError("SetRowAttrs() frame required")
            f = self.holder.frame(index, frame_name)
            if f is None:
                raise FrameNotFoundError()
            row_id, ok = c.uint_arg(f.row_label)
            if not ok:
                raise QueryError(f"SetRowAttrs() row field '{f.row_label}' required")
            attrs = dict(c.args)
            attrs.pop("frame", None)
            attrs.pop(f.row_label, None)
            by_frame.setdefault(frame_name, {}).setdefault(row_id, {}).update(attrs)

        for frame_name, items in by_frame.items():
            self.holder.frame(index, frame_name).row_attr_store.set_bulk_attrs(items)

        if not opt.remote:
            self._broadcast_with_hints(index, Query(calls=list(calls)), opt)
        return [None] * len(calls)

    def _execute_set_column_attrs(self, index: str, c: Call, opt: ExecOptions):
        """SetColumnAttrs (executor.go:943-998)."""
        self._check_writable("SetColumnAttrs()", opt)
        if self._spmd is not None and not opt.remote:
            return self._spmd.execute_pql(index, str(c))
        idx = self.holder.index(index)
        if idx is None:
            raise IndexNotFoundError()

        id_, ok = c.uint_arg("id")
        col_name = "id"
        if not ok:
            id_, ok = c.uint_arg(idx.column_label)
            if not ok:
                raise QueryError("SetColumnAttrs() id required")
            col_name = idx.column_label

        attrs = dict(c.args)
        attrs.pop(col_name, None)
        idx.column_attr_store.set_attrs(id_, attrs)

        if not opt.remote:
            self._broadcast_with_hints(index, Query(calls=[c]), opt)
        return None

    def _broadcast_query(self, index: str, q: Query, opt: ExecOptions):
        """Forward a write to every other node in parallel. EVERY
        future is awaited before any error is raised (the reference's
        first-error-wins, executor.go:833-855, leaks unawaited futures
        behind one slow replica), and the error lists every failed
        host. The client layer owns per-node retry; nodes that still
        fail are reported together via BroadcastError."""
        nodes = self._other_nodes()
        if not nodes:
            return
        futures = [
            (node, self._pool.submit(obs.wrap_ctx(self._exec_remote),
                                     node, index, q, None, opt))
            for node in nodes
        ]
        failures = []
        for node, fut in futures:
            try:
                fut.result()
            except Exception as err:  # noqa: BLE001 — collected below
                failures.append((node.host, err))
        if failures:
            raise BroadcastError(failures, len(nodes))

    def _broadcast_with_hints(self, index: str, q: Query,
                              opt: ExecOptions) -> None:
        """Attr broadcasts mutate the local store BEFORE fanning out,
        so a failed peer used to leave local state mutated with no
        repair path behind the error. With a hint manager wired, the
        failed hosts' calls are journaled and replayed — attrs
        converge the same way bits do and the write acks; without one
        (bare executors), the BroadcastError surfaces as before."""
        try:
            self._broadcast_query(index, q, opt)
        except BroadcastError as err:
            if self.hints is None:
                raise
            pql = str(q)
            for host, _e in err.failures:
                self.hints.enqueue_query(host, index, pql)

    # -- distributed fan-out -------------------------------------------------

    def _exec_remote(self, node, index: str, q: Query,
                     slices: Optional[Sequence[int]], opt: ExecOptions) -> list:
        """Remote execution via the injected client (executor.go:1000-1083).
        The query travels as its canonical PQL serialization, plus the
        REMAINING deadline budget when one is set (the client forwards
        it as X-Pilosa-Deadline-Us so downstream hops inherit it)."""
        if self.client is None:
            raise SliceUnavailableError()
        sp = obs.span("fanout", node=node.host,
                      slices=len(slices) if slices else 0)
        try:
            with sp, obs.profile.phase("fanout_remote"):
                fault.point("executor.fanout", node=node.host)
                opt.check_deadline(f"fanout to {node.host}")
                kw = {}
                if opt.deadline is not None:
                    # Only pass the kwarg when set: test fakes implement
                    # the positional execute_query seam without it.
                    kw["deadline"] = opt.deadline
                return self.client.execute_query(
                    node, index, str(q), slices or [], remote=True, **kw)
        finally:
            left = opt.deadline_left()
            if left is not None:
                # Tagged on exit so an expired hop shows a NEGATIVE
                # remaining budget in /debug/queries.
                sp.tag(deadline_left_us=int(left * 1e6))

    def _breaker_callable(self, opt: Optional[ExecOptions] = None):
        """The per-query breaker snapshot when `opt` carries one
        (execute() filled it — stable across re-splits), else the
        injected client's live breaker_state(host) callable, or None
        when it has no breaker registry (test fakes, single client)."""
        if opt is not None and opt.breaker_snapshot is not None:
            snap = opt.breaker_snapshot
            return lambda host: snap.get(host, "closed")
        state = getattr(self.client, "breaker_state", None)
        return state if callable(state) else None

    def _slices_by_node(self, nodes, index: str, slices: Sequence[int],
                        opt: Optional[ExecOptions] = None):
        """node -> slices owned, restricted to `nodes`
        (executor.go:1087-1101).

        Locality hierarchy (same-chip → same-pod-ICI → cross-node
        HTTP): a slice whose picked owner is a configured ICI peer
        (`[cluster] ici-hosts`) is folded into the LOCAL node's group —
        its shard is already addressable through this node's mesh, and
        the collective reduces over the interconnect — so only slices
        owned by hosts OUTSIDE the pod pay the HTTP ring."""
        local_node = (self.cluster.node_by_host(self.host)
                      if self.ici_hosts else None)
        if local_node is not None and local_node not in nodes:
            # e.g. a re-split that excluded this node: don't route an
            # ICI peer's slices back into the excluded local group.
            local_node = None
        breaker = self._breaker_callable(opt)
        # Bounded-staleness reads (X-Pilosa-Staleness > 0) spread over
        # every in-sync replica; strict reads (the default) and remote
        # legs keep the owner-only pick bit-for-bit.
        read_bound = (opt.staleness
                      if opt is not None and not opt.remote else 0.0)
        sclass = "bounded" if read_bound > 0 else "strict"
        m = {}
        for slice_ in slices:
            owners = [o for o in self.cluster.fragment_nodes(index, slice_)
                      if o in nodes]
            if opt is not None and opt.partial:
                # Membership-aware degradation: a JOINING node hasn't
                # received its slices yet and a DOWN node can't answer,
                # so in partial mode route only to serving replicas
                # (ACTIVE/LEAVING) and report the slice missing when
                # none remain — never hang on a non-serving owner.
                serving = [o for o in owners if o.state in SERVING_STATES]
                if not serving:
                    opt.missing_slices.append(slice_)
                    continue
                owners = serving
            elif not owners:
                raise SliceUnavailableError()
            # Bounded reads first try the follower-spread ladder:
            # pick_read_replica over in-sync replicas (breaker-closed,
            # epoch staleness within the client's bound, p2c by
            # gossiped queue depth). An empty candidate set falls DOWN
            # the ladder to the strict owner pick — never sideways to
            # a staler replica — and the fallback is counted.
            pick = None
            if read_bound > 0 and len(owners) > 1:
                pick = pick_read_replica(
                    owners, breaker,
                    staleness_ok=lambda h, s=slice_:
                        self.epochs.staleness_ok_slice(
                            h, index, s, read_bound),
                    queue_depth=self.epochs.queue_depth,
                    prefer=self.host,
                    ici_hosts=self.ici_hosts or None,
                    node_ok=self.peer_health_ok)
            if pick is not None:
                # "follower" = spread away from the ring primary
                # (owners[0] is ring order) — the label that proves
                # replicas actually absorb read load.
                self.read_stats.inc(
                    ("follower|" if pick.host != owners[0].host
                     else "owner|") + sclass)
            else:
                self.read_stats.inc(
                    ("fallback_owner|" if read_bound > 0
                     and len(owners) > 1 else "owner|") + sclass)
                # Prefer replicas the status-poll daemon currently
                # sees UP AND whose circuit breaker is closed; a slice
                # whose owners are all marked DOWN/open still tries
                # one (liveness is advisory — the reactive re-split
                # below is the authority, executor.go:1140-1151).
                pick = preferred_owner(
                    owners, breaker,
                    prefer=self.host if self.prefer_local_reads else None,
                    ici_hosts=self.ici_hosts or None)
            if (local_node is not None and pick.host != self.host
                    and pick.host in self.ici_hosts):
                # ICI-tier slice: serve it from the local mesh dispatch
                # (one psum over the pod fabric beats an HTTP leg).
                if opt is not None:
                    opt.used_ici = True
                pick = local_node
            m.setdefault(pick, []).append(slice_)
        return m

    def _map_reduce(self, index: str, slices: Sequence[int], c: Call,
                    opt: ExecOptions, map_fn, reduce_fn, batch_fn=None):
        """Cluster-wide map + reduce with node-failure re-split
        (executor.go:1103-1163).

        batch_fn, when given, serves a whole LOCAL slice batch in one
        device collective (the mesh serving path); a None return falls
        back to the per-slice map_fn fan-out. Remote nodes always go
        through the RPC path — each runs its own batch_fn on arrival."""
        if self.cluster is None or not self.cluster.nodes:
            return self._mapper_local(slices, map_fn, reduce_fn, batch_fn,
                                      opt.deadline)

        if opt.remote:
            # Already forwarded: restrict to the local node.
            nodes = [self.cluster.node_by_host(self.host)]
        else:
            nodes = list(self.cluster.nodes)

        return self._mapper(nodes, index, slices, c, opt, map_fn, reduce_fn,
                            batch_fn)

    @staticmethod
    def _transient_error(err: BaseException) -> bool:
        """Should this node failure trigger a replica re-split?
        Duck-typed on the `transient` attribute so the executor never
        imports the HTTP client (api -> handler -> executor cycle) and
        never parses messages: structured ClientErrors say so
        themselves, DeadlineExceededError says False, and anything
        unannotated (socket errors from fakes, pool crashes) defaults
        to transient — matching the reference's retry-anything
        behavior (executor.go:1140-1151). Non-transient remote errors
        (bad PQL, missing frame) would fail identically on every
        replica, so they propagate immediately."""
        transient = getattr(err, "transient", None)
        if transient is not None:
            return bool(transient)
        return not isinstance(err, QueryError)

    def _mapper(self, nodes, index: str, slices: Sequence[int], c: Call,
                opt: ExecOptions, map_fn, reduce_fn, batch_fn=None):
        m = self._slices_by_node(nodes, index, slices, opt)

        futures = {}
        for node, node_slices in m.items():
            # wrap_ctx: pool workers inherit the active trace span (a
            # fresh contextvars copy per submit), so the gather/fan-out
            # spans attach under this query, not nowhere.
            if node.host == self.host:
                fut = self._pool.submit(
                    obs.wrap_ctx(self._mapper_local), node_slices,
                    map_fn, reduce_fn, batch_fn, opt.deadline)
            elif not opt.remote:
                # This group actually pays a cross-node HTTP leg — the
                # query's tier is `http` no matter what else served.
                opt.used_http = True
                fut = self._pool.submit(
                    obs.wrap_ctx(self._exec_remote_one), node, index, c,
                    node_slices, opt)
            else:
                continue
            futures[fut] = (node, node_slices)

        result = None
        pending = set(futures)
        while pending:
            left = opt.deadline_left()
            if left is not None and left <= 0:
                for fut in pending:
                    fut.cancel()
                raise DeadlineExceededError(
                    f"fan-out wait: deadline exceeded by "
                    f"{-left * 1e6:.0f}us")
            done, pending = wait(pending, timeout=left,
                                 return_when=FIRST_COMPLETED)
            for fut in done:
                node, node_slices = futures[fut]
                try:
                    v = fut.result()
                except Exception as err:
                    if not self._transient_error(err):
                        for f in pending:
                            f.cancel()
                        raise
                    # Re-split this node's slices across remaining
                    # replicas (executor.go:1140-1151). The resplit
                    # span (resplit=1) makes the double failure visible
                    # in traces.
                    remaining = [n for n in nodes if n is not node]
                    try:
                        with obs.span("resplit", node=node.host,
                                      slices=len(node_slices), resplit=1):
                            v = self._mapper(remaining, index, node_slices,
                                             c, opt, map_fn, reduce_fn,
                                             batch_fn)
                    except SliceUnavailableError as resplit_err:
                        if opt.partial:
                            # No replica left for these slices: report
                            # them missing instead of failing.
                            opt.missing_slices.extend(node_slices)
                            continue
                        # Chain the re-split failure so the trace shows
                        # BOTH the root cause and the exhausted re-split.
                        raise err from resplit_err
                    if v is None:
                        # A partial-mode re-split that lost EVERY slice
                        # produced no result; nothing to fold.
                        continue
                result = reduce_fn(result, v)
        return result

    def _exec_remote_one(self, node, index: str, c: Call,
                         slices: Sequence[int], opt: ExecOptions):
        results = self._exec_remote(node, index, Query(calls=[c]), slices, opt)
        return results[0] if results else None

    def _mapper_local(self, slices: Sequence[int], map_fn, reduce_fn,
                      batch_fn=None, deadline: Optional[float] = None):
        """Local per-slice map + reduce (executor.go:1200-1236 runs a
        goroutine per slice; here the map fans out on the dedicated
        _slice_pool — NOT self._pool, see __init__ — and the reduce
        folds results in slice order, so the output is deterministic
        regardless of completion order). reduce_fn must handle prev=None
        by allocating a fresh accumulator — results never alias fragment
        row caches.

        When batch_fn serves the whole batch (mesh path), its result
        feeds reduce_fn directly — one device collective replaces the
        per-slice fan-out. `deadline` bounds each slice-result wait
        with the remaining budget (absolute monotonic instant)."""
        slices = list(slices)
        with obs.span("gather", slices=len(slices)) as gsp:
            if batch_fn is not None and slices:
                v = batch_fn(slices)
                if v is not None:
                    gsp.tag(mode="batch")
                    return reduce_fn(None, v)
            result = None
            if len(slices) <= 1:
                with obs.span("map", slices=len(slices)):
                    for slice_ in slices:
                        result = reduce_fn(result, map_fn(slice_))
                gsp.tag(mode="inline")
                return result
            gsp.tag(mode="fanout")
            futures = [self._slice_pool.submit(obs.wrap_ctx(map_fn), s)
                       for s in slices]
            try:
                with obs.span("reduce", slices=len(slices)):
                    for fut in futures:
                        if deadline is None:
                            result = reduce_fn(result, fut.result())
                            continue
                        left = deadline - time.monotonic()
                        if left <= 0:
                            raise DeadlineExceededError(
                                f"slice wait: deadline exceeded by "
                                f"{-left * 1e6:.0f}us")
                        try:
                            v = fut.result(timeout=left)
                        except TimeoutError:
                            raise DeadlineExceededError(
                                "slice wait: deadline exceeded")
                        result = reduce_fn(result, v)
            except BaseException:
                # Don't leave orphaned slice tasks burning pool workers
                # while the node-failure re-split re-executes these
                # slices.
                for fut in futures:
                    fut.cancel()
                raise
            return result
