"""ctypes loader + dispatch for the native host kernels.

The analog of the reference's runtime assembly dispatch
(roaring/assembly_asm.go:20,40-80 hasAsm + function-pointer selection):
on first import, build (if needed) and load native/libpilosa_native.so;
every kernel has a numpy fallback so the package works without a C++
toolchain. `has_native()` reports which path is live;
`PILOSA_TPU_NO_NATIVE=1` forces the fallback (the reference's
`go build -tags noasm` escape hatch).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libpilosa_native.so")

_U64P = ctypes.POINTER(ctypes.c_uint64)
_U32P = ctypes.POINTER(ctypes.c_uint32)
_U8P = ctypes.POINTER(ctypes.c_uint8)

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False
_FAIL_STAMP = os.path.join(_NATIVE_DIR, "build", ".build_failed")


def _src_mtime() -> float:
    try:
        return os.path.getmtime(os.path.join(_NATIVE_DIR,
                                             "pilosa_native.cpp"))
    except OSError:
        return 0.0


def _build() -> bool:
    if not os.path.isdir(_NATIVE_DIR):
        return False
    # A previously failed build is cached on disk and only retried when
    # the source changes, so toolchain-less machines pay the failed
    # compile once, not per process.
    try:
        if os.path.exists(_FAIL_STAMP) and                 float(open(_FAIL_STAMP).read() or 0) == _src_mtime():
            return False
    except (OSError, ValueError):
        pass
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR, "-s"], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_LIB_PATH)
    except Exception:  # noqa: BLE001 — no toolchain: numpy fallback
        try:
            os.makedirs(os.path.dirname(_FAIL_STAMP), exist_ok=True)
            with open(_FAIL_STAMP, "w") as f:
                f.write(str(_src_mtime()))
        except OSError:
            pass
        return False


def _load() -> Optional[ctypes.CDLL]:
    if os.environ.get("PILOSA_TPU_NO_NATIVE"):
        return None
    # Always run make: it is a cheap no-op when the .so is newer than the
    # source, and rebuilds a stale .so after source edits. A failed build
    # (no toolchain) still loads a previously built library if present.
    _build()
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.pilosa_popcnt_slice.restype = ctypes.c_uint64
    lib.pilosa_popcnt_slice.argtypes = [_U64P, ctypes.c_size_t]
    for name in ("and", "or", "xor", "andnot"):
        fn = getattr(lib, f"pilosa_popcnt_{name}_slice")
        fn.restype = ctypes.c_uint64
        fn.argtypes = [_U64P, _U64P, ctypes.c_size_t]
    for name, args in [
        ("intersect_sorted_u32", [_U32P, ctypes.c_size_t, _U32P,
                                  ctypes.c_size_t, _U32P]),
        ("intersection_count_sorted_u32", [_U32P, ctypes.c_size_t, _U32P,
                                           ctypes.c_size_t]),
        ("union_sorted_u32", [_U32P, ctypes.c_size_t, _U32P,
                              ctypes.c_size_t, _U32P]),
        ("difference_sorted_u32", [_U32P, ctypes.c_size_t, _U32P,
                                   ctypes.c_size_t, _U32P]),
        ("xor_sorted_u32", [_U32P, ctypes.c_size_t, _U32P,
                            ctypes.c_size_t, _U32P]),
        ("bitmap_to_values_u32", [_U64P, ctypes.c_size_t, _U32P]),
    ]:
        fn = getattr(lib, f"pilosa_{name}")
        fn.restype = ctypes.c_size_t
        fn.argtypes = args
    lib.pilosa_bitmap_contains_u32.restype = None
    lib.pilosa_bitmap_contains_u32.argtypes = [_U64P, _U32P,
                                               ctypes.c_size_t, _U8P]
    lib.pilosa_popcnt_blocks.restype = None
    lib.pilosa_popcnt_blocks.argtypes = [_U64P, ctypes.c_size_t,
                                         ctypes.c_size_t, _U64P]
    lib.pilosa_fold_blocks.restype = None
    lib.pilosa_fold_blocks.argtypes = [ctypes.POINTER(_U64P),
                                       ctypes.c_size_t, ctypes.c_int,
                                       ctypes.c_size_t, ctypes.c_size_t,
                                       _U64P, _U64P]
    return lib


def _get_lib() -> Optional[ctypes.CDLL]:
    """Deferred load: the (possibly blocking) build+dlopen happens on
    the first kernel call, not at import (roaring imports this module
    at its own import time)."""
    global _lib, _load_attempted
    if not _load_attempted:
        _load_attempted = True
        _lib = _load()
    return _lib

# ctypes call overhead beats the kernel below these sizes — numpy's SIMD
# handles small inputs better (measured: numpy wins at 1024-word
# containers, native wins >=8K words by 2-4x and 10x on value extraction).
POPCNT_NATIVE_MIN = 8192      # uint64 words
SORTED_NATIVE_MIN = 2048      # combined array elements


def has_native() -> bool:
    return _get_lib() is not None


def _p64(a: np.ndarray):
    return a.ctypes.data_as(_U64P)


def _p32(a: np.ndarray):
    return a.ctypes.data_as(_U32P)


# ---- popcount slices -------------------------------------------------------

def popcnt_slice(s: np.ndarray) -> int:
    lib = _get_lib()
    if (lib is not None and s.dtype == np.uint64 and s.flags.c_contiguous
            and len(s) >= POPCNT_NATIVE_MIN):
        return int(lib.pilosa_popcnt_slice(_p64(s), len(s)))
    return int(np.bitwise_count(s).sum())


def popcnt_blocks(s: np.ndarray, block_words: int = 1024) -> np.ndarray:
    """Per-block popcounts: (len(s)/block_words,) uint64 — ONE pass,
    one call, for per-container counts on the materializing path."""
    nblocks = len(s) // block_words
    lib = _get_lib()
    if (lib is not None and s.dtype == np.uint64 and s.flags.c_contiguous
            and len(s) >= POPCNT_NATIVE_MIN):
        out = np.empty(nblocks, dtype=np.uint64)
        lib.pilosa_popcnt_blocks(_p64(s), nblocks, block_words, _p64(out))
        return out
    return np.bitwise_count(s).reshape(nblocks, block_words) \
        .sum(axis=1, dtype=np.uint64)


_FOLD_OPS = {"and": 0, "or": 1, "andnot": 2}


def fold_blocks(leaves, op: str, block_words: int = 1024):
    """Fused flat fold + per-block popcount: (out, counts) for
    out = leaves[0] op leaves[1] op ... (left fold), or None when the
    native library is unavailable or inputs don't qualify — callers
    fall back to a numpy fold + popcnt_blocks (one extra result pass)."""
    lib = _get_lib()
    code = _FOLD_OPS.get(op)
    if (lib is None or code is None or len(leaves) < 2
            or any(a.dtype != np.uint64 or not a.flags.c_contiguous
                   or a.shape != leaves[0].shape for a in leaves)):
        return None
    n = leaves[0].size
    if n % block_words or n < POPCNT_NATIVE_MIN:
        return None
    nblocks = n // block_words
    out = np.empty(n, dtype=np.uint64)
    counts = np.empty(nblocks, dtype=np.uint64)
    ptrs = (_U64P * len(leaves))(*[
        a.ctypes.data_as(_U64P) for a in leaves])
    lib.pilosa_fold_blocks(ptrs, len(leaves), code, nblocks, block_words,
                           _p64(out), _p64(counts))
    return out, counts


def fold_count(blocks, tree) -> int:
    """Total popcount of a numbered op-tree (plan._tree_signature)
    folded over numpy uint64 blocks. Flat trees — one op over leaves in
    index order, the common Intersect/Union count — run through the
    fused C++ fold+per-block-popcount kernel in a single pass; nested
    or non-qualifying trees fall back to a numpy fold plus
    popcnt_slice (one extra materialized intermediate per op level)."""
    # Deferred import: bitops pulls in jax, and this module must stay
    # importable (and fast) in jax-free host tooling.
    from .bitops import flat_fold_op, fold_tree

    op = flat_fold_op(tree)
    if op is not None:
        r = fold_blocks(list(blocks), op)
        if r is not None:
            return int(r[1].sum())
    acc = fold_tree(tree, lambda i: blocks[i])
    return popcnt_slice(np.ascontiguousarray(acc))


def _popcnt_pair(name: str, np_op, s: np.ndarray, m: np.ndarray) -> int:
    lib = _get_lib()
    if (lib is not None and s.dtype == np.uint64 and m.dtype == np.uint64
            and s.flags.c_contiguous and m.flags.c_contiguous
            and len(s) == len(m) and len(s) >= POPCNT_NATIVE_MIN):
        return int(getattr(lib, f"pilosa_popcnt_{name}_slice")(
            _p64(s), _p64(m), len(s)))
    return int(np.bitwise_count(np_op(s, m)).sum())


def popcnt_and_slice(s, m) -> int:
    return _popcnt_pair("and", np.bitwise_and, s, m)


def popcnt_or_slice(s, m) -> int:
    return _popcnt_pair("or", np.bitwise_or, s, m)


def popcnt_xor_slice(s, m) -> int:
    return _popcnt_pair("xor", np.bitwise_xor, s, m)


def popcnt_andnot_slice(s, m) -> int:
    return _popcnt_pair("andnot", lambda a, b: a & ~b, s, m)


# ---- sorted-array kernels --------------------------------------------------

def intersect_sorted(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    lib = _get_lib()
    if lib is not None and len(a) + len(b) >= SORTED_NATIVE_MIN:
        a = np.ascontiguousarray(a, dtype=np.uint32)
        b = np.ascontiguousarray(b, dtype=np.uint32)
        out = np.empty(min(len(a), len(b)), dtype=np.uint32)
        k = lib.pilosa_intersect_sorted_u32(_p32(a), len(a), _p32(b),
                                             len(b), _p32(out))
        return out[:k]
    return np.intersect1d(a, b, assume_unique=True).astype(np.uint32)


def intersection_count_sorted(a: np.ndarray, b: np.ndarray) -> int:
    lib = _get_lib()
    if lib is not None and len(a) + len(b) >= SORTED_NATIVE_MIN:
        a = np.ascontiguousarray(a, dtype=np.uint32)
        b = np.ascontiguousarray(b, dtype=np.uint32)
        return int(lib.pilosa_intersection_count_sorted_u32(
            _p32(a), len(a), _p32(b), len(b)))
    return len(np.intersect1d(a, b, assume_unique=True))


def union_sorted(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    lib = _get_lib()
    if lib is not None and len(a) + len(b) >= SORTED_NATIVE_MIN:
        a = np.ascontiguousarray(a, dtype=np.uint32)
        b = np.ascontiguousarray(b, dtype=np.uint32)
        out = np.empty(len(a) + len(b), dtype=np.uint32)
        k = lib.pilosa_union_sorted_u32(_p32(a), len(a), _p32(b), len(b),
                                         _p32(out))
        return out[:k]
    return np.union1d(a, b).astype(np.uint32)


def difference_sorted(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    lib = _get_lib()
    if lib is not None and len(a) + len(b) >= SORTED_NATIVE_MIN:
        a = np.ascontiguousarray(a, dtype=np.uint32)
        b = np.ascontiguousarray(b, dtype=np.uint32)
        out = np.empty(len(a), dtype=np.uint32)
        k = lib.pilosa_difference_sorted_u32(_p32(a), len(a), _p32(b),
                                              len(b), _p32(out))
        return out[:k]
    return np.setdiff1d(a, b, assume_unique=True).astype(np.uint32)


def xor_sorted(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    lib = _get_lib()
    if lib is not None and len(a) + len(b) >= SORTED_NATIVE_MIN:
        a = np.ascontiguousarray(a, dtype=np.uint32)
        b = np.ascontiguousarray(b, dtype=np.uint32)
        out = np.empty(len(a) + len(b), dtype=np.uint32)
        k = lib.pilosa_xor_sorted_u32(_p32(a), len(a), _p32(b), len(b),
                                       _p32(out))
        return out[:k]
    return np.setxor1d(a, b, assume_unique=True).astype(np.uint32)


def bitmap_to_values(words: np.ndarray) -> np.ndarray:
    """Bitmap words -> sorted uint32 values (trailing-zero scan). The
    native path requires uint64 input and sizes the output by
    len(words) (values are < len(words)*64, so any word count is
    safe); anything else falls back to numpy."""
    lib = _get_lib()
    if (lib is not None and words.dtype == np.uint64
            and words.flags.c_contiguous and len(words) <= (1 << 26)):
        out = np.empty(len(words) << 6, dtype=np.uint32)
        k = lib.pilosa_bitmap_to_values_u32(_p64(words), len(words),
                                            _p32(out))
        return out[:k].copy()
    bits = np.unpackbits(np.ascontiguousarray(words).view(np.uint8),
                         bitorder="little")
    return np.nonzero(bits)[0].astype(np.uint32)


def bitmap_contains(words: np.ndarray, a: np.ndarray) -> np.ndarray:
    """Membership mask of sorted values `a` against bitmap words."""
    lib = _get_lib()
    if (lib is not None and words.dtype == np.uint64
            and words.flags.c_contiguous and len(a) >= SORTED_NATIVE_MIN
            and int(a[-1]) >> 6 < len(words)):  # a is sorted; match the
        # fallback's IndexError domain instead of reading out of bounds
        a = np.ascontiguousarray(a, dtype=np.uint32)
        mask = np.empty(len(a), dtype=np.uint8)
        lib.pilosa_bitmap_contains_u32(_p64(words), _p32(a), len(a),
                                        mask.ctypes.data_as(_U8P))
        return mask.astype(bool)
    return ((words[a >> np.uint32(6)] >> (a.astype(np.uint64)
                                          & np.uint64(63)))
            & np.uint64(1)).astype(bool)
