"""PQL call-tree → fused device computation.

The reference Count path materializes the intersection, then counts it
(executor.go:567-597 over roaring intersect kernels). Here a pure
bitmap-op tree — Bitmap (row on the standard view, column on the
inverse view) / Intersect / Union / Difference / Range — compiles to
ONE XLA computation per slice: gather each leaf row
as a (16, 2048) uint32 block from the fragment's HBM pool, combine
elementwise, popcount-reduce. No intermediate row ever hits HBM; this is
the "small compiler from pql.Call trees to jitted functions with a cache
keyed on tree shape" (SURVEY.md §7 hard parts).

Jit caching: the compiled function is cached on the tree's op-shape
signature (json of the nested op list), so repeated queries of the same
shape — the common case for a query workload — reuse the compiled
executable across row ids, fragments, and slices of the same pool
capacity.
"""

from __future__ import annotations

import functools
import json
import os
import time
import weakref
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import profile
from ..obs import span as obs_span
from ..ops.pool import gather_row
from ..core.view import VIEW_INVERSE, VIEW_STANDARD

# Call names evaluable on device, keyed to bitwise combiners.
_TREE_OPS = {"Intersect": "and", "Union": "or", "Difference": "andnot"}


def _tree_signature(node) -> object:
    """Canonical nested-list shape of a call tree; leaves are numbered in
    depth-first order."""
    counter = [0]

    def walk(n):
        if n[0] == "leaf":
            i = counter[0]
            counter[0] += 1
            return ["leaf", i]
        return [n[0]] + [walk(c) for c in n[1:]]

    return walk(node)


def device_slice_groups(slices, num_slices: int, n_devices: int):
    """Per-device slice-group sizes under the mesh's contiguous
    slice-axis sharding (build_sharded_index pads the slice axis to a
    multiple of the device count and NamedSharding(P(SLICE_AXIS))
    splits it into contiguous chunks). Device d therefore serves
    slices [d*chunk, (d+1)*chunk) — and since a slice carries EVERY
    row of its view (all BSI planes, the existence row, the sign row),
    any per-row combination stays device-local; only the final count
    partials cross the interconnect (psum). Returns a list of group
    sizes for the queried `slices`, devices with no queried slice
    omitted — the `?explain=true` device-group view of one mesh
    dispatch."""
    from .mesh import slice_device

    groups: Dict[int, int] = {}
    for s in slices:
        d = slice_device(s, num_slices, n_devices)
        groups[d] = groups.get(d, 0) + 1
    return [groups[d] for d in sorted(groups)]


def format_signature(sig: str, formats) -> str:
    """Tag a plan signature with the device container format(s) the
    launch serves from ("ss"/"sd"/"ds"/"dd" per slice group, or any
    descriptive tag). Sparse-path launches strike/quarantine under the
    TAGGED signature, so a broken sorted-array kernel quarantines only
    itself — the dense program for the same tree shape keeps serving."""
    if isinstance(formats, str):
        formats = (formats,)
    return sig + "|fmt=" + ",".join(formats)


def eval_tree(tree, leaves):
    """Evaluate a nested op-shape list over leaf (pool, dense_idx) pairs,
    returning the combined (16, 2048) uint32 block. Shared by the
    per-slice jitted path here and the mesh-sharded path
    (parallel.mesh); the combiner itself is ops.bitops.fold_tree, the
    same fold the Pallas tree-count kernel uses."""
    from ..ops.bitops import fold_tree

    def leaf(i):
        pool, dense_idx = leaves[i]
        return gather_row(pool, dense_idx)

    return fold_tree(tree, leaf)


@functools.lru_cache(maxsize=256)
def _compiled_count(sig: str):
    """Build + jit the evaluator for one tree shape."""
    tree = json.loads(sig)

    def count(leaves):
        blk = eval_tree(tree, leaves)
        return jax.lax.population_count(blk).astype(jnp.int32).sum()

    return jax.jit(count)


class CompiledPlanCache:
    """LRU of fused single-dispatch serving programs (the lowered
    PQL-tree → one-XLA-call fast path, mesh.compile_serve_count_fused).

    Keyed by (tree signature, leaf count, fragment widths — the
    per-leaf staged pool shapes — and backend): jit already keys
    compilation on argument shapes, but an unbounded miss stream (every
    novel width combination mints a program) would pin executables
    forever; the LRU bounds live programs the same way _compiled_count
    bounds the per-slice jits. The build runs under the lock so two
    racing first queries of one shape pay ONE compile (the GIL keeps
    the dict safe either way — the lock exists for the compile, exactly
    like serve._get_or_compile)."""

    def __init__(self, cap: int = 128):
        import threading
        from collections import OrderedDict

        self._mu = threading.Lock()
        self._fns: "OrderedDict[tuple, object]" = OrderedDict()
        self.cap = cap
        # Poisoned-plan set: tree signature -> monotonic expiry.
        # A signature lands here after repeated compile/runtime
        # failures (serve._note_plan_failure); while quarantined the
        # serving layer skips the device path for that shape entirely,
        # so one pathological query can't take the fast path down for
        # everyone. TTL'd: the fault may be transient (driver hiccup,
        # fixed by a restage), so the shape gets retried eventually.
        self._poisoned: Dict[str, float] = {}
        self.stats = {"hit": 0, "miss": 0, "evicted": 0,
                      "compile_us": 0, "quarantined": 0}

    @staticmethod
    def key(sig: str, words_t) -> tuple:
        """The canonical cache key for a fused count plan: tree shape,
        leaf count, per-leaf staged widths, backend. One definition so
        the serving layer and tests cannot disagree on it."""
        return (sig, len(words_t),
                tuple(tuple(w.shape) for w in words_t),
                jax.default_backend())

    def get_or_build(self, key: tuple, build):
        import time as _time

        with self._mu:
            fn = self._fns.get(key)
            if fn is not None:
                self._fns.move_to_end(key)  # LRU, not FIFO
                self.stats["hit"] += 1
                return fn
            t0 = _time.monotonic()
            fn = build()
            self.stats["compile_us"] += int(
                (_time.monotonic() - t0) * 1e6)
            if len(self._fns) >= self.cap:
                self._fns.popitem(last=False)
                self.stats["evicted"] += 1
            self._fns[key] = fn
            self.stats["miss"] += 1
            return fn

    def contains_sig(self, sig: str) -> bool:
        """Whether ANY cached plan was compiled for this tree shape —
        the EXPLAIN-surface peek (executor.explain). Key-prefix scan
        only: no staging, no mutation, no LRU reorder."""
        with self._mu:
            return any(k[0] == sig for k in self._fns)

    def quarantine(self, sig: str, ttl_s: float,
                   now: Optional[float] = None) -> None:
        """Poison a tree signature for ttl_s seconds and drop its
        cached programs (they may be the broken artifact)."""
        if now is None:
            now = time.monotonic()
        with self._mu:
            self._poisoned[sig] = now + float(ttl_s)
            self.stats["quarantined"] += 1
            for k in [k for k in self._fns if k[0] == sig]:
                del self._fns[k]

    def is_quarantined(self, sig: str,
                       now: Optional[float] = None) -> bool:
        """Whether this tree shape is currently poisoned. Expired
        entries are reaped on the way through, so an abandoned shape
        doesn't pin its entry forever."""
        if now is None:
            now = time.monotonic()
        with self._mu:
            expiry = self._poisoned.get(sig)
            if expiry is None:
                return False
            if now >= expiry:
                del self._poisoned[sig]
                return False
            return True

    def quarantined_sigs(self, now: Optional[float] = None) -> List[str]:
        """Live (unexpired) poisoned signatures — the ?explain=true /
        debug surface."""
        if now is None:
            now = time.monotonic()
        with self._mu:
            expired = [s for s, t in self._poisoned.items() if now >= t]
            for s in expired:
                del self._poisoned[s]
            return sorted(self._poisoned)

    def clear_quarantine(self, sig: Optional[str] = None) -> int:
        """Operator escape hatch: lift one signature's quarantine (or
        all of them). Returns how many entries were cleared."""
        with self._mu:
            if sig is None:
                n = len(self._poisoned)
                self._poisoned.clear()
                return n
            return 1 if self._poisoned.pop(sig, None) is not None else 0

    def __len__(self) -> int:
        return len(self._fns)


class CountPlan:
    """A compiled Count over one index's call tree. `count_slice` returns
    the slice's count, or None when this slice must fall back to the
    host path (e.g. a referenced fragment is absent)."""

    def __init__(self, holder, index: str, shape, leaves: List[tuple]):
        self.holder = holder
        self.index = index
        # leaves: [(frame, view, row_id, required)] in depth-first
        # order. required=False leaves (Range's time views) contribute
        # an empty block when the fragment is absent; a missing
        # required fragment sends the slice to the host path.
        self.leaves = leaves
        self._sig = json.dumps(_tree_signature(shape))
        self._fn = _compiled_count(self._sig)

    def count_slice(self, slice_: int) -> Optional[int]:
        staged = []
        fallback_pool = None
        for frame, view, row_id, required in self.leaves:
            frag = self.holder.fragment(self.index, frame, view, slice_)
            if frag is None:
                if required:
                    return None
                staged.append(None)
                continue
            pool, row_ids = frag.pool
            fallback_pool = (pool, row_ids)
            i = int(np.searchsorted(row_ids, np.uint64(row_id)))
            if i >= len(row_ids) or row_ids[i] != np.uint64(row_id):
                # Absent row: any dense index past the live keys gathers
                # all-zero (pool.py gather_row hit-mask).
                i = len(row_ids)
            staged.append((pool, jnp.int32(i)))
        if fallback_pool is None:
            return 0  # every leaf optional and absent
        # Absent optional fragments gather all-zero from any real pool
        # via an out-of-range dense index.
        pool, row_ids = fallback_pool
        leaf_args = tuple(
            arg if arg is not None else (pool, jnp.int32(len(row_ids)))
            for arg in staged)
        return int(self._fn(leaf_args))


class HostQueryCache:
    """Generation-validated caches for the cost-routed host path
    (VERDICT r3 #4): small-query workloads repeat, and the reference's
    own answer to repeated counts is a cache (rank/row caches,
    cache.go:126-275, fragment.go:404-408). Two layers, both validated
    against the owning fragments' mutation generations — any write
    bumps the generation, so a hit can never serve stale data (and
    generations are monotonic, so an entry stored against a snapshot
    that a concurrent write raced past can never validate later):

      - leaf blocks: (fragment, row) -> dense (16*1024,) uint64 words.
        Extraction is ~70% of a routed count's cost (measured 0.15 ms
        of 0.24 ms for an 8-leaf slice); blocks are immutable by
        convention (fold_tree never mutates operands).
      - per-slice counts: (index, sig, rows, slice) -> int. A repeat
        query re-reads only generations (~µs) instead of re-folding.

    Memory: blocks are 128 KB each, LRU-bounded (256 ≈ 32 MB); count
    entries are tuples. Thread-safe: one small lock, dict-sized ops,
    never held across extraction or folding. Lock order: a fragment's
    _mu may be held while taking this lock, never the reverse."""

    _BLOCKS_MAX = 256
    _MEMO_MAX = 4096
    _QUERY_MAX = 4096

    def __init__(self):
        import threading
        from collections import OrderedDict as _OD

        self._mu = threading.Lock()
        self._blocks: "_OD[tuple, tuple]" = _OD()
        self._memo: "_OD[tuple, tuple]" = _OD()
        self._query: "_OD[tuple, tuple]" = _OD()
        self._matrix: "_OD[tuple, tuple]" = _OD()
        self._matrix_bytes = 0
        self.stats = {"block_hit": 0, "block_miss": 0,
                      "memo_hit": 0, "memo_miss": 0,
                      "query_hit": 0, "query_miss": 0, "query_reval": 0,
                      "matrix_hit": 0, "matrix_miss": 0}

    # Leaf dense-matrix cache budget (bytes): a matrix is one leaf
    # row's (S, 16384) uint64 stack — 12.6 MB at 96 slices, 126 MB at
    # the 960-slice headline — so the bound is bytes, not entries.
    # Read per call like the sibling PILOSA_TPU_HBM_BUDGET_MB knob
    # (serve.py), so tests and operators can set it after import.
    @staticmethod
    def _matrix_budget_bytes() -> int:
        return int(os.environ.get(
            "PILOSA_TPU_MATRIX_CACHE_MB", "384")) << 20

    def matrix_get(self, key: tuple, epoch: int):
        """Whole-batch dense leaf matrix ((S, 16384) uint64), validated
        by the process-wide MUTATION_EPOCH. Coarse on purpose: on a
        miss the matrix restacks from the (generation-validated) block
        layer below, so a write costs one memcpy-speed rebuild, not
        re-extraction."""
        with self._mu:
            e = self._matrix.get(key)
            if e is not None and e[0] == epoch:
                self._matrix.move_to_end(key)
                self.stats["matrix_hit"] += 1
                return e[1]
            self.stats["matrix_miss"] += 1
            return None

    def matrix_put(self, key: tuple, epoch: int, matrix) -> None:
        with self._mu:
            old = self._matrix.pop(key, None)
            if old is not None:
                self._matrix_bytes -= old[1].nbytes
            self._matrix[key] = (epoch, matrix)
            self._matrix_bytes += matrix.nbytes
            budget = self._matrix_budget_bytes()
            while (self._matrix_bytes > budget
                   and len(self._matrix) > 1):
                _, (_, m) = self._matrix.popitem(last=False)
                self._matrix_bytes -= m.nbytes

    def query_get(self, key: tuple, epoch: int, s_epoch: Optional[int] = None):
        """Whole-QUERY count memo, validated by the process-wide
        MUTATION_EPOCH (core.fragment): the warm path for a repeated
        read-only Count is one dict probe + one int compare — no
        re-lowering, no plan construction, no per-slice generation
        walk.

        Second tier (r5): an entry stored with a TOKEN — the
        structural epoch plus every touched fragment's generation at
        store time — REVALIDATES after an epoch bump from an
        unrelated write: if the structural epoch is unchanged (no
        fragment/frame/index create/delete, no label or time-quantum
        change anywhere), the fragment SET the query touches is
        intact, so comparing recorded generations is a complete
        staleness check. A pass re-stamps the entry at the current
        epoch — sound because a generation can't move without bumping
        MUTATION_EPOCH (fragment._log_append/_log_reset), so the next
        bump forces another generation walk. Entries hold WEAK
        fragment refs; a dead ref never validates. Without a token
        (non-lowerable tree, oversized fan-out) any bump invalidates,
        the r4 behavior."""
        with self._mu:
            e = self._query.get(key)
            if e is not None and e[0] == epoch:
                self._query.move_to_end(key)
                self.stats["query_hit"] += 1
                return e[1]
            if e is None or e[2] is None or s_epoch is None:
                # No token to walk: the miss is decided — count it in
                # THIS critical section (the common path takes one
                # lock round-trip, not two).
                self.stats["query_miss"] += 1
                return None
        # The generation walk can span thousands of weakref derefs
        # (token cap 8192): run it OUTSIDE the lock — this class
        # promises dict-sized critical sections only — then re-take
        # it to re-stamp, tolerating a concurrent replace (the walk
        # validated OUR entry's count, so returning it is correct
        # regardless of what the entry says now).
        tok = e[2]
        if tok[0] == s_epoch and all(
                (fr := f()) is not None and fr.generation == g
                for f, g in tok[1]):
            with self._mu:
                if self._query.get(key) is e:
                    self._query[key] = (epoch, e[1], tok)
                    self._query.move_to_end(key)
                self.stats["query_reval"] += 1
            return e[1]
        with self._mu:
            self.stats["query_miss"] += 1
        return None

    def query_peek(self, key: tuple, epoch: int) -> bool:
        """EXPLAIN-surface probe: would a repeat of this query serve
        from the whole-query memo at the CURRENT epoch? No stats
        mutation, no LRU reorder, no token walk (a token-revalidating
        entry reports False — EXPLAIN under-promises rather than
        touching generations)."""
        with self._mu:
            e = self._query.get(key)
            return e is not None and e[0] == epoch

    def query_put(self, key: tuple, epoch: int, count: int,
                  s_epoch: Optional[int] = None,
                  frag_gens: Optional[tuple] = None) -> None:
        """`frag_gens`: ((fragment, generation), ...) read BEFORE the
        fold — a write racing the fold moved some generation past its
        recorded value, so the token can never validate (same
        pre-compute rationale as `epoch`)."""
        token = None
        if frag_gens is not None and s_epoch is not None:
            token = (s_epoch,
                     tuple((weakref.ref(f), g) for f, g in frag_gens))
        with self._mu:
            self._query[key] = (epoch, count, token)
            self._query.move_to_end(key)
            while len(self._query) > self._QUERY_MAX:
                self._query.popitem(last=False)

    def block_get(self, frag, row_id: int, gen: int):
        key = (id(frag), int(row_id))
        with self._mu:
            e = self._blocks.get(key)
            # Identity check pins against id() recycling: entries hold
            # a WEAK fragment ref (a deleted index's fragments — and
            # their multi-MB parsed storage — must stay collectable),
            # and a live weakref keeps the target's id stable.
            if e is not None and e[0]() is frag and e[1] == gen:
                self._blocks.move_to_end(key)
                self.stats["block_hit"] += 1
                return e[2]
            self.stats["block_miss"] += 1
            return None

    def block_put(self, frag, row_id: int, gen: int, words) -> None:
        key = (id(frag), int(row_id))
        with self._mu:
            self._blocks[key] = (weakref.ref(frag), gen, words)
            self._blocks.move_to_end(key)
            while len(self._blocks) > self._BLOCKS_MAX:
                self._blocks.popitem(last=False)

    def memo_get(self, key: tuple, snapshot: tuple):
        """`snapshot` holds LIVE (fragment_or_None, gen) pairs; stored
        entries hold weak refs — a dead ref never validates."""
        with self._mu:
            e = self._memo.get(key)
            if e is not None and len(e[0]) == len(snapshot) and all(
                    (f0() if f0 is not None else None) is f1 and g0 == g1
                    for (f0, g0), (f1, g1) in zip(e[0], snapshot)):
                self._memo.move_to_end(key)
                self.stats["memo_hit"] += 1
                return e[1]
            self.stats["memo_miss"] += 1
            return None

    def memo_put(self, key: tuple, snapshot: tuple, count: int) -> None:
        with self._mu:
            self._memo[key] = (tuple(
                (weakref.ref(f) if f is not None else None, g)
                for f, g in snapshot), count)
            self._memo.move_to_end(key)
            while len(self._memo) > self._MEMO_MAX:
                self._memo.popitem(last=False)


class HostCountPlan:
    """Fused HOST Count over a lowered tree — what cost-routed small
    queries run (executor._route_to_host).

    Per slice: each leaf row expands to one dense (16*1024,) uint64
    word block straight from its fragment's containers (array
    containers expand via values_to_bitmap_words), the tree folds with
    numpy bitwise ops, and ONE native C++ popcount (ops/native.py, the
    amd64-assembly stand-in, reference assembly_amd64.s:47-115) counts
    the result. No roaring containers materialize and no intermediate
    cardinalities are computed — measured ~5x faster than the
    materializing Row fold it replaces on the 8-row single-slice bench
    config (1.37 ms -> ~0.25 ms), closing most of the gap to the raw
    kernel floor that the reference's own materialize-then-count path
    (executor.go:567-597, SURVEY.md §3.2 note) never closes either.

    An absent fragment or row contributes an all-zero block (empty-row
    semantics, matching execute_bitmap_call_slice)."""

    _ZEROS = None  # shared all-zero block (read-only by convention)

    def __init__(self, holder, index: str, shape, leaves: List[tuple],
                 cache: Optional[HostQueryCache] = None):
        self.holder = holder
        self.index = index
        self.leaves = leaves
        # Numbered depth-first once (CountPlan does the same); leaves
        # were collected in the same depth-first order.
        self._sig = _tree_signature(shape)
        self.cache = cache
        if cache is not None:
            self._sig_json = json.dumps(self._sig)
            self._leaves_key = tuple(
                (f, v, int(r), bool(q)) for f, v, r, q in leaves)
            # Unique (frame, view) pairs, order-stable: the generation
            # snapshot covers each underlying fragment once.
            self._uniq_views = list(dict.fromkeys(
                (f, v) for f, v, _r, _q in leaves))

    @classmethod
    def _zeros(cls):
        if cls._ZEROS is None:
            cls._ZEROS = np.zeros(16 * 1024, dtype=np.uint64)
        return cls._ZEROS

    def _gen_snapshot(self, slice_: int) -> tuple:
        """(fragment_or_None, generation) per unique leaf view of this
        slice — the validation token for the count memo."""
        snap = []
        for frame, view in self._uniq_views:
            frag = self.holder.fragment(self.index, frame, view, slice_)
            if frag is None:
                snap.append((None, -1))
            else:
                with frag._mu:
                    snap.append((frag, frag.generation))
        return tuple(snap)

    def _leaf_words(self, frame, view, row_id, slice_):
        frag = self.holder.fragment(self.index, frame, view, slice_)
        if frag is None:
            return self._zeros()
        cache = self.cache
        with frag._mu:
            frag.ensure_loaded()
            if cache is not None:
                gen = frag.generation
                w = cache.block_get(frag, row_id, gen)
                if w is not None:
                    return w
            storage = frag.storage
            base = row_id * 16
            keys = storage.keys
            import bisect

            lo = bisect.bisect_left(keys, base)
            if lo >= len(keys) or keys[lo] >= base + 16:
                return self._zeros()
            out = np.zeros(16 * 1024, dtype=np.uint64)
            i = lo
            while i < len(keys) and keys[i] < base + 16:
                sub = keys[i] - base
                out[sub * 1024:(sub + 1) * 1024] = storage.containers[i].words()
                i += 1
        if cache is not None:
            cache.block_put(frag, row_id, gen, out)
        return out

    def count_slice(self, slice_: int) -> Optional[int]:
        from ..ops import native

        cache = self.cache
        key = snap = None
        if cache is not None:
            snap = self._gen_snapshot(slice_)
            key = (self.index, self._sig_json, self._leaves_key, slice_)
            n = cache.memo_get(key, snap)
            if n is not None:
                return n

        # fold_count folds with the ONE shared combiner the XLA and
        # Pallas paths use (bitops.fold_tree over numpy blocks), except
        # that flat trees — one op, leaves in order, i.e. the common
        # Intersect/Union count — run through the fused native
        # fold+popcount kernel in a single pass with no materialized
        # intermediate. It never mutates operands, so cached blocks are
        # safe to feed directly.
        blocks = [self._leaf_words(frame, view, row_id, slice_)
                  for frame, view, row_id, _req in self.leaves]
        n = native.fold_count(blocks, self._sig)
        if cache is not None:
            # Generations are monotonic: if a write raced between the
            # snapshot and the block reads, this entry's snapshot is
            # already stale and can never validate — stale data cannot
            # be served, only recomputed.
            cache.memo_put(key, snap, n)
        return n

    def count_slices(self, slices) -> Optional[int]:
        """Whole-batch host count: per-slice counts summed INLINE.
        Serves as the executor's batch_fn for cost-routed queries — a
        thread-pool fan-out per slice costs more than the fold itself
        once the memo layer answers most slices in microseconds. A
        declining slice (count_slice -> None, per its contract) makes
        the whole batch decline: the executor then falls back to the
        per-slice map_fn, which handles None slice-by-slice."""
        slices = list(slices)
        with obs_span("host_fold", slices=len(slices)) as sp, \
                profile.phase("host_fold"):
            prof = profile.current()
            total = 0
            for s in slices:
                t0 = time.monotonic_ns() if prof is not None else 0
                n = self.count_slice(s)
                if n is None:
                    sp.tag(declined=True)
                    return None
                total += n
                if prof is not None:
                    # Every leaf block is a dense 16x1024 uint64 read
                    # (128 KiB), memo hits aside — the fold's memory
                    # traffic, which the host roofline divides by.
                    prof.add_bytes("bytes_touched_hbm",
                                   len(self.leaves) * 16 * 1024 * 8)
                    prof.add_slice(
                        slice=int(s), engine="host_fold", count=int(n),
                        us=round((time.monotonic_ns() - t0) / 1e3, 1))
            return total


class HostMaterializePlan(HostCountPlan):
    """Fused HOST materialization of a Bitmap-ROOTED (non-Count) tree
    (VERDICT r4 #5): fold dense leaf word blocks with numpy bitwise ops
    — sharing HostCountPlan's generation-validated leaf-block cache —
    and lift the folded words straight into one roaring segment per
    slice (Bitmap.from_dense_words), instead of materializing every
    intermediate operand as roaring containers and two-pointer-merging
    them pairwise. The reference pays that per-operand materialization
    too (bitmap.go:85-134, SURVEY.md §3.2 note); here the only roaring
    object ever built is the RESULT.

    A device-program variant (fold on TPU, fetch packed words) was
    considered and rejected: the payload is the whole result bitmap, so
    readback bandwidth — not fold FLOPs — is the binding cost, and the
    host fold reads the same bytes without the H2D/D2H round trip. The
    device path's advantage is reductions (counts, TopN limbs), where
    the readback is scalars."""

    def materialize_slice(self, slice_: int):
        """The folded slice-local roaring Bitmap, or None when no leaf
        has data here (caller skips the empty segment)."""
        from ..ops.bitops import fold_tree
        from ..roaring import Bitmap as RBitmap

        blocks = []
        nonzero = False
        for frame, view, row_id, _req in self.leaves:
            w = self._leaf_words(frame, view, row_id, slice_)
            nonzero = nonzero or w is not self._zeros()
            blocks.append(w)
        if not nonzero:
            return None
        acc = fold_tree(self._sig, lambda i: blocks[i])
        return RBitmap.from_dense_words(acc, own=True)

    def _leaf_matrix(self, frame, view, row_id, slices):
        """One leaf row's dense (len(slices), 16*1024) uint64 stack,
        through the epoch-validated matrix cache; a miss restacks from
        the per-slice block cache (memcpy speed, not re-extraction)."""
        from ..core.fragment import MUTATION_EPOCH

        cache = self.cache
        key = epoch = None
        if cache is not None:
            epoch = MUTATION_EPOCH.n
            key = (self.index, frame, view, int(row_id), tuple(slices))
            m = cache.matrix_get(key, epoch)
            if m is not None:
                return m
        m = np.empty((len(slices), 16 * 1024), dtype=np.uint64)
        for j, s in enumerate(slices):
            m[j] = self._leaf_words(frame, view, row_id, s)
        if cache is not None:
            cache.matrix_put(key, epoch, m)
        return m

    def materialize_row(self, slices):
        """Fold the WHOLE slice batch in array-level numpy ops and lift
        the result into one Row: per-tree-node cost is one vectorized
        pass over (S, 16384) matrices — the same bytes/pass as the raw
        bitwise kernel — followed by ONE native per-block popcount
        (form selection + segment count cache in a single call) and
        view-backed container construction (from_dense_words own=True:
        zero copies of result words). The per-slice variant above pays
        ~10 numpy dispatches per slice; at 96 slices that tax alone
        exceeded the fold."""
        from ..core.row import Row
        from ..ops import native
        from ..ops.bitops import fold_tree
        from ..roaring.bitmap import (
            ARRAY_MAX_SIZE,
            Bitmap as RBitmap,
            Container,
            bitmap_to_values,
        )

        slices = list(slices)
        mats = [self._leaf_matrix(f, v, r, slices)
                for f, v, r, _req in self.leaves]
        # Flat tree + native lib: ONE pass computes the fold and the
        # per-block counts together (the result never gets re-read for
        # counting). Nested trees fall back to the shared numpy fold
        # plus one native count pass.
        fused = None
        sig = self._sig
        if all(c[0] == "leaf" for c in sig[1:]):
            ordered = [mats[c[1]] for c in sig[1:]]
            fused = native.fold_blocks(ordered, sig[0])
        if fused is not None:
            flat, counts = fused
            acc = flat.reshape(len(slices), 16 * 1024)
        else:
            acc = fold_tree(sig, lambda i: mats[i])  # (S, 16384)
            if any(acc is m for m in mats):
                # A degenerate shape can fold to a leaf itself;
                # containers must never view CACHED matrix memory
                # (they are handed out own=True below).
                acc = acc.copy()
            counts = native.popcnt_blocks(acc.reshape(-1))

        # Containers are built in ONE flat loop over the nonzero
        # (slice, key) pairs as python ints — numpy scalar indexing
        # per container measured ~3x the whole fold at 96 slices.
        blocks = list(acc.reshape(-1, 1024))  # views minted at C speed
        counts_l = counts.tolist()
        nz = np.flatnonzero(counts).tolist()
        # Dense containers normally keep VIEWS into `acc` (zero-copy —
        # the result Row collectively owns most of it anyway). But when
        # only a sliver of the batch is nonzero, one retained container
        # view would pin the WHOLE (S, 16384) allocation for the Row's
        # lifetime; below a quarter occupancy, copy the referenced
        # blocks and let the big buffer free.
        copy_blocks = len(nz) * 4 < len(blocks)
        per_slice = counts.reshape(-1, 16).sum(axis=1).tolist()
        row = Row()
        segments = row.segments
        seg_counts = row._counts
        cnew, bnew = Container.__new__, RBitmap.__new__
        keys_append = containers_append = None
        cur_slice = -1
        for idx in nz:
            s_j = idx >> 4
            if s_j != cur_slice:
                cur_slice = s_j
                cur = bnew(RBitmap)
                cur.keys = keys = []
                cur.containers = containers = []
                cur.op_writer = None
                cur.op_n = 0
                keys_append = keys.append
                containers_append = containers.append
                s = slices[s_j]
                segments[s] = cur
                seg_counts[s] = per_slice[s_j]
            n = counts_l[idx]
            c = cnew(Container)
            c.shared = False
            if n <= ARRAY_MAX_SIZE:
                c.array = bitmap_to_values(blocks[idx])
                c.bitmap = None
            else:
                c.array = None
                c.bitmap = blocks[idx].copy() if copy_blocks \
                    else blocks[idx]
            keys_append(idx & 15)
            containers_append(c)
        return row


def _lower_tree(holder, index: str, c, leaves: List[tuple]):
    """Call → nested shape list, collecting leaves; None if not lowerable."""
    if c.name == "Bitmap":
        from ..executor import DEFAULT_FRAME

        idx = holder.index(index)
        if idx is None:
            return None
        frame = c.args.get("frame") or DEFAULT_FRAME
        f = idx.frame(frame)
        if f is None:
            return None
        try:
            row_id, row_ok = c.uint_arg(f.row_label)
            col_id, col_ok = c.uint_arg(idx.column_label)
        except TypeError:
            return None
        if row_ok and not col_ok:
            leaves.append((frame, VIEW_STANDARD, row_id, True))
            return ["leaf"]
        if col_ok and not row_ok and f.inverse_enabled:
            # Bitmap(columnID=..) reads the inverse view; the slice set
            # stays whatever the caller mapped (matching the host path,
            # which fetches the inverse fragment per mapped slice —
            # executor.go:420-465 semantics).
            leaves.append((frame, VIEW_INVERSE, col_id, True))
            return ["leaf"]
        return None  # both/neither/disabled-inverse → host path
    if c.name == "Range":
        from ..pql.ast import Cond

        if any(isinstance(v, Cond) for v in c.args.values()):
            from ..bsi.lower import lower_cond

            return lower_cond(holder, index, c, leaves)
        return _lower_range(holder, index, c, leaves)
    op = _TREE_OPS.get(c.name)
    if op is None or not c.children:
        return None
    parts = []
    for child in c.children:
        sub = _lower_tree(holder, index, child, leaves)
        if sub is None:
            return None
        parts.append(sub)
    return [op] + parts


def _lower_range(holder, index: str, c, leaves: List[tuple]):
    """Range(frame, <row>, start, end) → OR over its time-quantum view
    leaves (executor.go:490-546 semantics: absent view fragments are
    empty, not errors — the leaves are optional)."""
    from ..core import views_by_time_range
    from ..executor import DEFAULT_FRAME, parse_time

    idx = holder.index(index)
    if idx is None:
        return None
    frame = c.args.get("frame") or DEFAULT_FRAME
    f = idx.frame(frame)
    if f is None:
        return None
    try:
        row_id, ok = c.uint_arg(f.row_label)
    except TypeError:
        return None  # invalid arg type → host path owns error reporting
    start, end = c.args.get("start"), c.args.get("end")
    if not ok or not isinstance(start, str) or not isinstance(end, str):
        return None
    try:
        views = views_by_time_range(VIEW_STANDARD, parse_time(start),
                                    parse_time(end), f.time_quantum)
    except ValueError:
        return None
    if not views or len(views) > 32:
        # No quantum → host path (returns empty). A very wide unaligned
        # cover (fine quanta) would jit a huge fused OR and churn the
        # compile cache; incremental host unions win there.
        return None
    for v in views:
        leaves.append((frame, v, row_id, False))
    if len(views) == 1:
        return ["leaf"]
    return ["or"] + [["leaf"]] * len(views)


def compile_count_plan(holder, index: str, tree) -> Optional[CountPlan]:
    """Compile Count's child tree for fused device eval; None when the
    tree doesn't qualify (unknown frames, non-integer args, a Bitmap
    with both/neither of row and column args, columnID without
    inverse_enabled, over-wide Range covers, ...)."""
    leaves: List[tuple] = []
    shape = _lower_tree(holder, index, tree, leaves)
    if shape is None or shape == ["leaf"] and not leaves:
        return None
    return CountPlan(holder, index, shape, leaves)
