"""Replication-epoch tracking + the epoch-keyed result cache (ISSUE 18).

Every fragment carries a monotonic mutation epoch (core/fragment.py):
one bump per applied op, floor-raised by anti-entropy and hint replay
so the counters stay comparable across replicas. This module is the
COORDINATOR side of that story:

  - `EpochTracker` aggregates what this node knows about every
    replica's epochs — its own holder's live fragments, the write
    fan-out it coordinates, and the `(fragment -> epoch, queue_depth)`
    digests peers serve at GET /internal/epochs (pulled on the status
    poll, piggybacked on gossip). A replica's staleness is measured in
    WRITES-BEHIND (its epoch vs the max known), mapped to wall-clock
    through the tracker's first-seen history: the age of the oldest
    write a replica is missing is the time since this node first
    learned of the epoch past it.

  - `ResultCache` is the coordinator-level LRU keyed by
    `(plan signature, slices, max fragment epoch over touched slices)`
    — the clustered generalization of the executor's single-node memo
    (parallel/plan.HostQueryCache): entries never revalidate, they are
    keyed to an epoch and a newer epoch is simply a different key, so
    stale results invalidate instead of serving.

Staleness semantics (documented in README "Read-path scale-out"): a
bound of X means "reads reflect every write this coordinator has known
about for at least X" — knowledge arrives at local apply / write
fan-out instantly and at digest cadence for writes coordinated
elsewhere. The conservative fallbacks below (history exhausted, digest
missing) all fail CLOSED: an ineligible replica costs a hop up the
ladder, a wrongly-eligible one would serve stale data.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

from ..obs import StatMap

# Per-key history ring: (epoch, first-seen monotonic time) pairs. 256
# entries cover a deep backlog; anything deeper falls back to
# "unknown-old", which is ineligible (fail closed).
HISTORY_MAX = 256

DEFAULT_RESULT_CACHE_SIZE = 4096


def fragment_key(index: str, frame: str, view: str, slice_: int) -> str:
    """Canonical digest key for one fragment replica."""
    return f"{index}/{frame}/{view}/{slice_}"


class EpochTracker:
    """What this coordinator knows about every replica's write
    progress. Thread-safe; all methods are cheap dict work (the write
    path calls observe_local per coordinated op)."""

    def __init__(self):
        self._mu = threading.Lock()
        # key -> max epoch known from ANY source (the freshness bar).
        self._max: Dict[str, int] = {}
        # key -> deque[(epoch, first_seen_monotonic)] appended when the
        # known max advances; key -> highest epoch dropped off the ring
        # (staleness older than the ring is "unknown-old" = ineligible).
        self._history: Dict[str, deque] = {}
        self._dropped: Dict[str, int] = {}
        # host -> (epochs dict, queue_depth, received_monotonic).
        self._digests: Dict[str, Tuple[Dict[str, int], int, float]] = {}
        # (index, slice) -> set of full keys: the placement layer and
        # the result cache ask questions per SLICE (they don't know
        # which frames a plan touches yet), so keep a secondary index
        # instead of scanning every key per query.
        self._slice_keys: Dict[Tuple[str, int], set] = {}

    # -- feeds ---------------------------------------------------------------

    def observe_local(self, key: str, epoch: int,
                      now: Optional[float] = None) -> None:
        """A write this node applied or coordinated (fan-out ack), or a
        local fragment's live epoch: the known max advances NOW."""
        with self._mu:
            self._note_locked(key, int(epoch),
                              time.monotonic() if now is None else now)

    def observe_digest(self, host: str, epochs: Dict[str, int],
                       queue_depth: int = 0,
                       now: Optional[float] = None) -> None:
        """A peer's GET /internal/epochs answer (status poll / gossip)."""
        t = time.monotonic() if now is None else now
        epochs = {str(k): int(v) for k, v in (epochs or {}).items()}
        with self._mu:
            self._digests[host] = (epochs, int(queue_depth), t)
            for k, e in epochs.items():
                self._note_locked(k, e, t)

    def forget_host(self, host: str) -> None:
        with self._mu:
            self._digests.pop(host, None)

    def _note_locked(self, key: str, epoch: int, now: float) -> None:
        if epoch <= self._max.get(key, 0):
            return
        if key not in self._max:
            parts = key.split("/")
            if len(parts) == 4:
                try:
                    sk = (parts[0], int(parts[3]))
                except ValueError:
                    sk = None
                if sk is not None:
                    self._slice_keys.setdefault(sk, set()).add(key)
        self._max[key] = epoch
        h = self._history.get(key)
        if h is None:
            h = self._history[key] = deque()
        h.append((epoch, now))
        while len(h) > HISTORY_MAX:
            dropped_epoch, _ = h.popleft()
            if dropped_epoch > self._dropped.get(key, 0):
                self._dropped[key] = dropped_epoch

    # -- reads ---------------------------------------------------------------

    def max_epoch(self, key: str) -> int:
        with self._mu:
            return self._max.get(key, 0)

    def max_epoch_many(self, keys) -> int:
        """Max known epoch over a set of fragment keys (the result
        cache's epoch component: any touched fragment advancing busts
        the entry)."""
        with self._mu:
            return max((self._max.get(k, 0) for k in keys), default=0)

    def host_epoch(self, host: str, key: str) -> int:
        with self._mu:
            d = self._digests.get(host)
            return d[0].get(key, 0) if d else 0

    def queue_depth(self, host: str) -> int:
        with self._mu:
            d = self._digests.get(host)
            return d[1] if d else 0

    def digest_age(self, host: str) -> Optional[float]:
        with self._mu:
            d = self._digests.get(host)
            return None if d is None else time.monotonic() - d[2]

    def max_epoch_slices(self, index: str, slices) -> int:
        """Max known epoch over every tracked fragment of (index,
        slice) for slice in slices — the result cache's epoch token.
        Conservative across frames on purpose: a write to ANY frame of
        a touched slice busts entries for plans over that slice."""
        with self._mu:
            best = 0
            for s in slices:
                for k in self._slice_keys.get((index, int(s)), ()):
                    e = self._max.get(k, 0)
                    if e > best:
                        best = e
            return best

    def staleness_ok(self, host: str, keys, bound_s: float,
                     now: Optional[float] = None) -> bool:
        """Is `host` an eligible bounded-staleness read target for the
        fragments in `keys`? True when, for every key, the host is
        fully caught up OR the oldest write it is missing is younger
        than `bound_s`. Fails closed: no digest from the host, or a
        backlog deeper than the history ring, is ineligible."""
        t = time.monotonic() if now is None else now
        with self._mu:
            return self._staleness_ok_locked(host, keys, bound_s, t)

    def staleness_ok_slice(self, host: str, index: str, slice_: int,
                           bound_s: float,
                           now: Optional[float] = None) -> bool:
        """staleness_ok over every tracked fragment of one (index,
        slice) — the per-slice question `pick_read_replica` asks (the
        placement layer doesn't know which frames the plan touches, so
        it requires the replica fresh-enough on ALL of them)."""
        t = time.monotonic() if now is None else now
        with self._mu:
            keys = self._slice_keys.get((index, int(slice_)), ())
            return self._staleness_ok_locked(host, keys, bound_s, t)

    def _staleness_ok_locked(self, host: str, keys, bound_s: float,
                             t: float) -> bool:
        d = self._digests.get(host)
        if d is None:
            return False
        host_epochs = d[0]
        for key in keys:
            known = self._max.get(key, 0)
            if known <= 0:
                continue  # no known writes: nothing to miss
            he = host_epochs.get(key, 0)
            if he >= known:
                continue  # fully caught up on this fragment
            if he < self._dropped.get(key, 0):
                return False  # older than the ring remembers
            # First history entry past the host's epoch = when this
            # node learned of the oldest write the host is missing.
            first_seen = None
            for epoch, seen in self._history.get(key, ()):
                if epoch > he:
                    first_seen = seen
                    break
            if first_seen is None or (t - first_seen) > bound_s:
                return False
        return True

    def snapshot(self) -> dict:
        """/debug/vars `epochs` section."""
        with self._mu:
            return {
                "tracked_fragments": len(self._max),
                "peers": {
                    h: {"fragments": len(d[0]), "queue_depth": d[1],
                        "age_s": round(time.monotonic() - d[2], 3)}
                    for h, d in self._digests.items()
                },
            }


class ResultCache:
    """Coordinator-level LRU of whole-query results keyed by
    (plan signature + slices, epoch). Invalidation IS the key: the
    caller computes `epoch` as the max fragment epoch over every slice
    the plan touches (EpochTracker.max_epoch_many), so any observed
    write produces a different key and the old entry dies by LRU or by
    the explicit same-plan invalidate below. Events are counted for
    pilosa_result_cache_events_total{event}."""

    def __init__(self, cap: int = DEFAULT_RESULT_CACHE_SIZE):
        self.cap = max(1, int(cap))
        self._mu = threading.Lock()
        # base_key -> (epoch, value)
        self._entries: "OrderedDict[tuple, Tuple[int, object]]" = \
            OrderedDict()
        self.stats = StatMap()

    def get(self, base_key: tuple, epoch: int):
        """The cached value for this plan at exactly `epoch`, or None.
        A surviving entry keyed to an OLDER epoch is dropped and
        counted as an invalidation (the write that advanced the epoch
        is what killed it)."""
        with self._mu:
            ent = self._entries.get(base_key)
            if ent is None:
                self.stats.inc("miss")
                return None
            if ent[0] != epoch:
                del self._entries[base_key]
                self.stats.inc("invalidate")
                self.stats.inc("miss")
                return None
            self._entries.move_to_end(base_key)
            self.stats.inc("hit")
            return ent[1]

    def put(self, base_key: tuple, epoch: int, value) -> None:
        with self._mu:
            self._entries[base_key] = (int(epoch), value)
            self._entries.move_to_end(base_key)
            while len(self._entries) > self.cap:
                self._entries.popitem(last=False)
                self.stats.inc("evict")

    def invalidate(self, base_key: tuple) -> None:
        """Drop one entry (shadow-verify mismatch quarantine)."""
        with self._mu:
            if self._entries.pop(base_key, None) is not None:
                self.stats.inc("invalidate")

    def bypass(self) -> None:
        """A query that consulted the cache but was ineligible (strict
        read, non-cacheable plan) — counted so hit-rate math has a
        denominator that covers the whole read stream."""
        self.stats.inc("bypass")

    def __len__(self) -> int:
        with self._mu:
            return len(self._entries)

    def snapshot(self) -> dict:
        s = self.stats.copy()
        with self._mu:
            size = len(self._entries)
        hits = s.get("hit", 0)
        misses = s.get("miss", 0)
        return {"size": size, "cap": self.cap,
                "hit_rate": round(hits / (hits + misses), 4)
                if hits + misses else None, **s}
