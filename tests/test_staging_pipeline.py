"""Pipelined H2D staging: chunked transfers must be invisible except
in speed. The chunk boundary math, the packer-thread handoff, and the
per-chunk byte accounting all get exercised against the single-put
path on the same bitmaps.
"""

import numpy as np
import pytest

from pilosa_tpu import SLICE_WIDTH
from pilosa_tpu.obs import profile as profile_mod
from pilosa_tpu.parallel import build_sharded_index, default_mesh
from pilosa_tpu.parallel.mesh import _stage_chunk_bytes, _stage_pipeline
from pilosa_tpu.roaring import Bitmap


def make_bitmaps(num_slices, rows=(3, 9), per_row=400, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for s in range(num_slices):
        b = Bitmap()
        for r in rows:
            cols = rng.choice(SLICE_WIDTH, size=per_row, replace=False)
            b.add_many((np.uint64(r) << np.uint64(20))
                       | cols.astype(np.uint64))
        out.append(b)
    return out


def stage(bitmaps, mesh=None, chunk_mb=None, monkeypatch=None):
    stats = {}
    if chunk_mb is not None:
        monkeypatch.setenv("PILOSA_TPU_STAGE_CHUNK_MB", str(chunk_mb))
    idx, row_ids = build_sharded_index(bitmaps, mesh, stats_out=stats)
    return idx, row_ids, stats


def test_chunk_size_env():
    assert _stage_chunk_bytes() == 64 << 20  # the r12 default


def test_multi_chunk_equals_single_put(monkeypatch):
    # 20 slices x 256 KB (two 16-container rows) at a 1 MB chunk =
    # 4 slices/chunk = 5 chunks; the assembled pool must be
    # bit-identical to the one-put stage.
    bitmaps = make_bitmaps(20)
    idx1, rows1, st1 = stage(bitmaps, chunk_mb=4096,
                             monkeypatch=monkeypatch)
    assert st1["h2d_chunks"] == 1
    idx2, rows2, st2 = stage(bitmaps, chunk_mb=1, monkeypatch=monkeypatch)
    assert st2["h2d_chunks"] == 5
    assert st2["h2d_chunk_slices"] == 4
    np.testing.assert_array_equal(rows1, rows2)
    np.testing.assert_array_equal(np.asarray(idx1.keys),
                                  np.asarray(idx2.keys))
    np.testing.assert_array_equal(np.asarray(idx1.words),
                                  np.asarray(idx2.words))
    # Same bytes shipped either way, counted per chunk.
    assert st1["h2d_bytes"] == st2["h2d_bytes"]


def test_sharded_multi_chunk_equivalence(monkeypatch):
    # Across the 8-device test mesh each shard pipelines its own
    # chunks; the assembled sharded pool must match the single-put one.
    mesh = default_mesh()
    bitmaps = make_bitmaps(16, seed=7)
    idx1, _, st1 = stage(bitmaps, mesh, chunk_mb=4096,
                         monkeypatch=monkeypatch)
    idx2, _, st2 = stage(bitmaps, mesh, chunk_mb=1, monkeypatch=monkeypatch)
    assert st2["h2d_chunks"] >= st1["h2d_chunks"]
    np.testing.assert_array_equal(np.asarray(idx1.words),
                                  np.asarray(idx2.words))
    np.testing.assert_array_equal(np.asarray(idx1.keys),
                                  np.asarray(idx2.keys))


def test_cumulative_byte_accounting(monkeypatch):
    # Every chunk's dispatch adds to bytes_staged AS IT SHIPS (the
    # profile-phase fix): the profiled total equals the stats total,
    # which equals words + keys bytes exactly.
    bitmaps = make_bitmaps(20, seed=3)
    prof = profile_mod.QueryProfile()
    tok = profile_mod.activate(prof)
    try:
        idx, _, stats = stage(bitmaps, chunk_mb=1, monkeypatch=monkeypatch)
    finally:
        profile_mod.deactivate(tok)
        prof.finish()
    d = prof.to_dict()
    words_b = int(np.prod(np.asarray(idx.words).shape)) * 4
    keys_b = int(np.prod(np.asarray(idx.keys).shape)) * 4
    assert stats["h2d_bytes"] == words_b + keys_b
    assert d["bytes"]["bytes_staged"] == stats["h2d_bytes"]
    assert d["phases_us"].get("stage_h2d", 0) > 0


def test_pipeline_pack_error_propagates():
    calls = []

    def pack(lo, hi):
        if lo >= 4:
            raise ValueError("pack exploded")
        calls.append((lo, hi))
        return np.zeros((hi - lo, 4), dtype=np.uint32)

    with pytest.raises(ValueError, match="pack exploded"):
        _stage_pipeline(pack, [(0, 4), (4, 8)], None)
    assert calls == [(0, 4)]


def test_pipeline_single_chunk_skips_thread():
    seen = []
    out = _stage_pipeline(
        lambda lo, hi: np.ones((hi - lo, 4), dtype=np.uint32),
        [(0, 2)], None, on_chunk=seen.append)
    assert len(out) == 1
    assert seen == [2 * 4 * 4]


def test_pipeline_chunk_order_and_bytes():
    sizes = []
    out = _stage_pipeline(
        lambda lo, hi: np.full((hi - lo, 4), lo, dtype=np.uint32),
        [(0, 2), (2, 5), (5, 6)], None, on_chunk=sizes.append)
    assert [int(np.asarray(p)[0, 0]) for p in out] == [0, 2, 5]
    assert sizes == [2 * 16, 3 * 16, 1 * 16]
