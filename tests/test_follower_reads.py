"""Read-path resilience (ISSUE 18): replication-epoch monotonicity,
bounded-staleness follower reads, and the epoch-keyed result cache.

The epoch is the correctness currency of the whole read-path story —
a replica's freshness and a cache entry's validity are both judged by
it — so the tests here pin the invariant from every direction it can
be attacked:

  - per-op bump + durable sidecar: an epoch NEVER regresses across a
    clean reopen, a kill -9 WAL replay (subprocess, slow), hint-drain
    convergence, anti-entropy read-repair, or a bulk /import;
  - strict reads (staleness 0, the default) stay byte-identical to
    the owner-only path and never consult the result cache;
  - cache hits are provably epoch-fresh: a write to a touched slice
    invalidates (different key), and the shadow-verify sampler's
    mismatch counter stays at zero.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from pilosa_tpu import SLICE_WIDTH
from pilosa_tpu.api import InternalClient
from pilosa_tpu.config import Config
from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.core.syncer import FragmentSyncer
from pilosa_tpu.executor import SHADOW_STATS
from pilosa_tpu.parallel import Node
from pilosa_tpu.parallel.cluster import pick_read_replica
from pilosa_tpu.parallel.epochs import (
    EpochTracker,
    ResultCache,
    fragment_key,
)
from pilosa_tpu.parallel.hints import HintManager
from pilosa_tpu.server import Server

CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "crash_child.py")


def free_ports(n):
    out = []
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        out.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return out


def _post(host, path, body=b"", headers=None, timeout=10):
    req = urllib.request.Request(f"http://{host}{path}", data=body,
                                 headers=headers or {}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read()


# -- fragment epoch invariants (in-process, tier-1) ---------------------------


class TestFragmentEpoch:
    def test_epoch_counts_ops_and_survives_reopen(self, tmp_path):
        f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
        f.open()
        for col in range(7):
            f.set_bit(1, col)
        f.clear_bit(1, 3)  # clears are mutations too
        assert f.epoch == 8
        f.close()
        f2 = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
        f2.open()
        # reopen = sidecar base + replayed ops; never lower
        assert f2.epoch == 8
        f2.set_bit(2, 0)
        assert f2.epoch == 9
        f2.close()

    def test_advance_epoch_is_floor_only(self, tmp_path):
        f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
        f.open()
        try:
            f.set_bit(1, 0)
            assert f.advance_epoch(10) == 10
            # raising to a LOWER value is a no-op, not a regression
            assert f.advance_epoch(3) == 10
            assert f.epoch == 10
            f.set_bit(1, 1)
            assert f.epoch == 11
        finally:
            f.close()

    def test_advanced_epoch_base_survives_reopen(self, tmp_path):
        f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
        f.open()
        f.set_bit(1, 0)
        f.advance_epoch(42)
        f.close()
        f2 = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
        f2.open()
        try:
            assert f2.epoch == 42
        finally:
            f2.close()


# -- EpochTracker (coordinator-side freshness judge) --------------------------


class TestEpochTracker:
    KEY = "i/f/standard/0"

    def test_max_is_monotonic_across_feeds(self):
        t = EpochTracker()
        t.observe_local(self.KEY, 5, now=1.0)
        t.observe_digest("h:1", {self.KEY: 3}, now=2.0)  # behind: no-op
        assert t.max_epoch(self.KEY) == 5
        t.observe_digest("h:1", {self.KEY: 9}, now=3.0)
        assert t.max_epoch(self.KEY) == 9

    def test_no_digest_fails_closed(self):
        t = EpochTracker()
        t.observe_local(self.KEY, 5, now=1.0)
        assert not t.staleness_ok("h:1", [self.KEY], 60.0, now=2.0)

    def test_staleness_bounded_by_oldest_missing_write(self):
        t = EpochTracker()
        t.observe_local(self.KEY, 5, now=100.0)
        t.observe_local(self.KEY, 6, now=103.0)
        t.observe_digest("h:1", {self.KEY: 5}, now=103.0)
        # h:1 is missing only epoch 6, first seen at t=103
        assert t.staleness_ok("h:1", [self.KEY], 2.0, now=104.0)
        assert not t.staleness_ok("h:1", [self.KEY], 2.0, now=106.0)
        # caught up: eligible at any bound
        t.observe_digest("h:1", {self.KEY: 6}, now=200.0)
        assert t.staleness_ok("h:1", [self.KEY], 0.001, now=999.0)

    def test_history_ring_truncation_fails_closed(self):
        t = EpochTracker()
        for e in range(1, 400):  # deeper than HISTORY_MAX=256
            t.observe_local(self.KEY, e, now=float(e))
        t.observe_digest("h:1", {self.KEY: 1}, now=400.0)
        # the ring no longer remembers when epoch 2 appeared:
        # unknown-old is ineligible no matter the bound
        assert not t.staleness_ok("h:1", [self.KEY], 1e9, now=400.0)

    def test_max_epoch_slices_spans_frames(self):
        t = EpochTracker()
        t.observe_local("i/f/standard/0", 4, now=1.0)
        t.observe_local("i/g/standard/0", 9, now=1.0)
        t.observe_local("i/f/standard/1", 2, now=1.0)
        assert t.max_epoch_slices("i", [0]) == 9
        assert t.max_epoch_slices("i", [0, 1]) == 9
        assert t.max_epoch_slices("i", [1]) == 2
        assert t.max_epoch_slices("j", [0]) == 0

    def test_forget_host_drops_eligibility(self):
        t = EpochTracker()
        t.observe_local(self.KEY, 3, now=1.0)
        t.observe_digest("h:1", {self.KEY: 3}, now=1.0)
        assert t.staleness_ok("h:1", [self.KEY], 1.0, now=2.0)
        t.forget_host("h:1")
        assert not t.staleness_ok("h:1", [self.KEY], 1.0, now=2.0)


# -- ResultCache (epoch-keyed LRU) --------------------------------------------


class TestResultCache:
    def test_epoch_mismatch_invalidates_instead_of_serving(self):
        rc = ResultCache(cap=8)
        rc.put(("i", "sig", (0,)), 5, 42)
        assert rc.get(("i", "sig", (0,)), 5) == 42
        # a write advanced the epoch: the old entry must DIE, not serve
        assert rc.get(("i", "sig", (0,)), 6) is None
        assert len(rc) == 0
        s = rc.stats.copy()
        assert s.get("invalidate") == 1 and s.get("hit") == 1

    def test_lru_evicts_oldest_and_counts(self):
        rc = ResultCache(cap=2)
        rc.put(("a",), 1, 1)
        rc.put(("b",), 1, 2)
        assert rc.get(("a",), 1) == 1  # touch: "a" is now MRU
        rc.put(("c",), 1, 3)
        assert rc.get(("b",), 1) is None  # "b" was LRU
        assert rc.get(("a",), 1) == 1
        assert rc.stats.copy().get("evict") == 1


# -- pick_read_replica (placement) --------------------------------------------


class TestPickReadReplica:
    def _owners(self):
        return [Node("h:1"), Node("h:2"), Node("h:3")]

    def test_local_replica_always_wins(self):
        pick = pick_read_replica(self._owners(),
                                 staleness_ok=lambda h: True,
                                 prefer="h:2")
        assert pick.host == "h:2"

    def test_open_breaker_and_stale_replicas_filtered(self):
        pick = pick_read_replica(
            self._owners(),
            breaker_state=lambda h: "open" if h == "h:1" else "closed",
            staleness_ok=lambda h: h != "h:3")
        assert pick.host == "h:2"

    def test_none_when_no_replica_eligible(self):
        assert pick_read_replica(self._owners(),
                                 staleness_ok=lambda h: False) is None

    def test_p2c_prefers_shallower_queue(self):
        class _Rnd:
            def sample(self, xs, n):
                return [xs[0], xs[1]]

        pick = pick_read_replica(
            self._owners(),
            staleness_ok=lambda h: True,
            queue_depth=lambda h: {"h:1": 9, "h:2": 1}.get(h, 0),
            rnd=_Rnd())
        assert pick.host == "h:2"


# -- hint drain carries epochs (replay-plane fake) ----------------------------


class _EpochReplayClient:
    """Replay fake that records the advance_epochs call the drainer
    makes AFTER the hinted ops land."""

    def __init__(self):
        self.calls = []

    def _bound(self, host):
        self.host = host
        return self

    def execute_query(self, node, index, pql, slices, remote=True, **kw):
        self.calls.append(("query", pql))
        return [True]

    def import_bits(self, index, frame, slice_, rows, cols, ts=None,
                    remote=False):
        self.calls.append(("import", slice_))

    def advance_epochs(self, epochs):
        self.calls.append(("advance", dict(epochs)))
        return len(epochs)


class TestHintEpochCarriage:
    def test_replay_floor_raises_after_ops_land(self, tmp_path):
        cli = _EpochReplayClient()
        m = HintManager(str(tmp_path / "hints"),
                        client_factory=cli._bound, drain_interval=3600)
        key = fragment_key("i", "f", "standard", 0)
        m.enqueue_query("h:1", "i", "SetBit(columnID=1)",
                        epochs={key: 7})
        m.enqueue_import("h:1", "i", "f", 0, [1], [2], None,
                         epochs={key: 8})
        assert m.drain_once() == 2
        m.close()
        # advance follows its op — an epoch never vouches for bits
        # that have not landed yet
        assert cli.calls == [("query", "SetBit(columnID=1)"),
                             ("advance", {key: 7}),
                             ("import", 0),
                             ("advance", {key: 8})]

    def test_payload_without_epochs_stays_compatible(self, tmp_path):
        cli = _EpochReplayClient()
        m = HintManager(str(tmp_path / "hints"),
                        client_factory=cli._bound, drain_interval=3600)
        m.enqueue_query("h:1", "i", "SetBit(columnID=1)")
        assert m.drain_once() == 1
        m.close()
        assert cli.calls == [("query", "SetBit(columnID=1)")]


# -- anti-entropy reconciles epochs (read-repair) -----------------------------


class _SyncPeer:
    """Peer fake for FragmentSyncer: serves a fixed block map and
    records epoch advances."""

    def __init__(self, blocks):
        self.blocks = blocks
        self.advanced = []

    def fragment_blocks(self, index, frame, view, slice_, **kw):
        return dict(self.blocks)

    def advance_epochs(self, epochs):
        self.advanced.append(dict(epochs))
        return len(epochs)


class TestSyncerEpochReconcile:
    def _frag(self, tmp_path):
        f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
        f.open()
        for col in range(5):
            f.set_bit(1, col)
        return f

    def test_converged_peer_is_floor_raised(self, tmp_path):
        f = self._frag(tmp_path)
        try:
            peer = _SyncPeer(dict(f.blocks()))  # bit-identical
            nodes = [Node("local:1"), Node("peer:1")]
            s = FragmentSyncer(f, "local:1", nodes,
                               client_factory=lambda h: peer)
            s.sync_fragment()
            key = fragment_key("i", "f", "standard", 0)
            assert peer.advanced == [{key: f.epoch}]
        finally:
            f.close()

    def test_dirty_peer_waits_for_next_pass(self, tmp_path):
        f = self._frag(tmp_path)
        try:
            peer = _SyncPeer({})  # diverged: peer has nothing
            nodes = [Node("local:1"), Node("peer:1")]
            s = FragmentSyncer(f, "local:1", nodes,
                               client_factory=lambda h: peer)
            s.sync_block = lambda bid: None  # content merge not under test
            s.sync_fragment()
            # an epoch must never vouch for bits the peer hasn't got
            assert peer.advanced == []
        finally:
            f.close()


# -- cluster HTTP: strict identity, cache freshness, epoch carriage -----------


def _boot(tmp_path, hosts, i):
    c = Config()
    c.data_dir = str(tmp_path / f"frnode{i}")
    c.host = hosts[i]
    c.cluster_hosts = list(hosts)
    c.replica_n = 3
    c.hint_drain_interval = 3600  # tests drive the drainer explicitly
    c.anti_entropy_interval = 3600
    c.polling_interval = 3600
    c.sched_enabled = False
    s = Server(c)
    s.open()
    return s


def _cluster3(tmp_path):
    hosts = [f"127.0.0.1:{p}" for p in free_ports(3)]
    return hosts, [_boot(tmp_path, hosts, i) for i in range(3)]


class TestStrictReadsUnchanged:
    def test_strict_is_byte_identical_and_bypasses_cache(self, tmp_path):
        hosts, servers = _cluster3(tmp_path)
        try:
            cli = InternalClient(hosts[0])
            cli.create_index("q")
            cli.create_frame("q", "f")
            for col in (0, 3, SLICE_WIDTH + 1):
                cli.execute_query(
                    None, "q", f"SetBit(rowID=1, frame=f, columnID={col})",
                    [], remote=False)
            pql = b"Count(Bitmap(rowID=1, frame=f))"
            st0, body0 = _post(hosts[0], "/index/q/query", pql)
            st1, body1 = _post(hosts[0], "/index/q/query", pql,
                               headers={"X-Pilosa-Staleness": "0"})
            st2, body2 = _post(hosts[0], "/index/q/query", pql,
                               headers={"X-Pilosa-Staleness": "0ms"})
            assert st0 == st1 == st2 == 200
            # staleness 0 (default, bare-number, and duration spellings)
            # IS the strict path: byte-for-byte identical
            assert body0 == body1 == body2
            assert json.loads(body0)["results"] == [3]
            picks = servers[0].executor.read_stats.copy()
            assert picks.get("owner|strict", 0) >= 3
            assert not any(k.endswith("|bounded") for k in picks)
            # the result cache was never consulted for strict reads
            rc = servers[0].executor.result_cache.stats.copy()
            assert rc.get("hit", 0) == 0 and rc.get("miss", 0) == 0
        finally:
            for s in servers:
                s.close()


class TestResultCacheFreshness:
    def test_write_invalidates_and_shadow_stays_clean(self, tmp_path):
        hosts, servers = _cluster3(tmp_path)
        try:
            cli = InternalClient(hosts[0])
            cli.create_index("q")
            cli.create_frame("q", "f")
            for col in range(4):
                cli.execute_query(
                    None, "q", f"SetBit(rowID=1, frame=f, columnID={col})",
                    [], remote=False)
            ex = servers[0].executor
            pql = b"Count(Bitmap(rowID=1, frame=f))"
            hdr = {"X-Pilosa-Staleness": "200ms"}

            ex.result_cache_verify_1_in = 0  # phase 1: plain hits
            _, b1 = _post(hosts[0], "/index/q/query", pql, headers=hdr)
            _, b2 = _post(hosts[0], "/index/q/query", pql, headers=hdr)
            assert json.loads(b1)["results"] == [4]
            assert b1 == b2
            s = ex.result_cache.stats.copy()
            assert s.get("miss", 0) >= 1 and s.get("hit", 0) >= 1

            # a write to a touched slice busts the entry: the next
            # bounded read recomputes — NEVER serves the stale count
            cli.execute_query(
                None, "q", "SetBit(rowID=1, frame=f, columnID=9)", [],
                remote=False)
            _, b3 = _post(hosts[0], "/index/q/query", pql, headers=hdr)
            assert json.loads(b3)["results"] == [5]
            s2 = ex.result_cache.stats.copy()
            assert s2.get("invalidate", 0) >= s.get("invalidate", 0) + 1

            # phase 2: shadow-verify EVERY hit; mismatches stay at 0
            ex.result_cache_verify_1_in = 1
            checks0 = SHADOW_STATS.copy().get("checks:result-cache", 0)
            mis0 = SHADOW_STATS.copy().get("mismatch:result-cache", 0)
            for _ in range(5):
                _, bv = _post(hosts[0], "/index/q/query", pql,
                              headers=hdr)
                assert json.loads(bv)["results"] == [5]
            shadow = SHADOW_STATS.copy()
            assert shadow.get("checks:result-cache", 0) > checks0
            assert shadow.get("mismatch:result-cache", 0) == mis0
        finally:
            for s in servers:
                s.close()


class TestClusterEpochCarriage:
    KEY = fragment_key("q", "f", "standard", 0)

    def test_hint_drain_converges_epochs(self, tmp_path):
        hosts, servers = _cluster3(tmp_path)
        try:
            cli = InternalClient(hosts[0])
            cli.create_index("q")
            cli.create_frame("q", "f")
            cli.execute_query(
                None, "q", "SetBit(rowID=1, frame=f, columnID=0)", [],
                remote=False)
            servers[2].close()
            for col in range(1, 21):
                cli.execute_query(
                    None, "q", f"SetBit(rowID=1, frame=f, columnID={col})",
                    [], remote=False)
            coord_epoch = servers[0].holder.fragment(
                "q", "f", "standard", 0).epoch
            assert coord_epoch == 21
            # coordinator's tracker learned each fan-out epoch locally
            assert servers[0].executor.epochs.max_epoch(self.KEY) == 21

            servers[2] = _boot(tmp_path, hosts, 2)
            replica = servers[2].holder.fragment("q", "f", "standard", 0)
            before = replica.epoch
            servers[0].client.breakers.for_host(hosts[2]).record_success()
            assert servers[0].hints.wait_drained(30)
            after = servers[2].holder.fragment(
                "q", "f", "standard", 0).epoch
            assert after >= before  # never regresses
            assert after >= coord_epoch  # caught up to the coordinator
        finally:
            for s in servers:
                try:
                    s.close()
                except Exception:
                    pass

    def test_import_bits_advances_every_replica(self, tmp_path):
        hosts, servers = _cluster3(tmp_path)
        try:
            cli = InternalClient(hosts[0])
            cli.create_index("q")
            cli.create_frame("q", "f")
            cli.import_bits("q", "f", 0, [1] * 30, list(range(30)))
            epochs = [s.holder.fragment("q", "f", "standard", 0).epoch
                      for s in servers]
            assert all(e > 0 for e in epochs)
            # the coordinator's tracker observed the post-apply epoch
            assert servers[0].executor.epochs.max_epoch(self.KEY) \
                == epochs[0]
            # a second import only moves epochs FORWARD, everywhere
            cli.import_bits("q", "f", 0, [2] * 5, list(range(5)))
            for s, e0 in zip(servers, epochs):
                assert s.holder.fragment(
                    "q", "f", "standard", 0).epoch > e0
        finally:
            for s in servers:
                s.close()

    def test_digest_endpoint_serves_holder_epochs(self, tmp_path):
        hosts, servers = _cluster3(tmp_path)
        try:
            cli = InternalClient(hosts[0])
            cli.create_index("q")
            cli.create_frame("q", "f")
            cli.execute_query(
                None, "q", "SetBit(rowID=1, frame=f, columnID=0)", [],
                remote=False)
            for h in hosts:
                digest = InternalClient(h).epoch_digest()
                assert digest["epochs"].get(self.KEY, 0) >= 1
                assert "queue_depth" in digest
            # the advance plane floor-raises, never regresses
            assert InternalClient(hosts[1]).advance_epochs(
                {self.KEY: 99}) == 1
            assert servers[1].holder.fragment(
                "q", "f", "standard", 0).epoch == 99
            assert InternalClient(hosts[1]).advance_epochs(
                {self.KEY: 5}) == 0
            assert servers[1].holder.fragment(
                "q", "f", "standard", 0).epoch == 99
        finally:
            for s in servers:
                s.close()


# -- kill -9 mid-stream: WAL replay must not regress the epoch (slow) ---------


@pytest.mark.slow
class TestEpochSurvivesKillMinusNine:
    def _spawn(self, data_dir, port):
        return subprocess.Popen(
            [sys.executable, CHILD, str(data_dir), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})

    def _wait_ready(self, proc, port, deadline_s=120):
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                _, err = proc.communicate(timeout=10)
                raise AssertionError(
                    f"child died during boot: {err.decode()[-2000:]}")
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/version", timeout=2).read()
                return
            except Exception:  # noqa: BLE001 — still booting
                time.sleep(0.2)
        raise AssertionError("child never became ready")

    def _digest(self, port):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/internal/epochs",
                timeout=10) as r:
            return json.loads(r.read().decode())["epochs"]

    def test_epoch_monotonic_across_wal_replay(self, tmp_path):
        key = fragment_key("i", "f", "standard", 0)
        port = free_ports(1)[0]
        proc = self._spawn(tmp_path, port)
        acked = 0
        try:
            self._wait_ready(proc, port)
            _post(f"127.0.0.1:{port}", "/index/i")
            _post(f"127.0.0.1:{port}", "/index/i/frame/f")
            for col in range(80):
                st, _ = _post(
                    f"127.0.0.1:{port}", "/index/i/query",
                    f"SetBit(rowID=1, frame=f, columnID={col})".encode())
                if st == 200:
                    acked += 1
            assert acked == 80
            epoch_before = self._digest(port).get(key, 0)
            assert epoch_before == 80
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=30)
            # restart on the SAME data dir: sidecar base + WAL replay
            # must restore an epoch >= every acked mutation
            port2 = free_ports(1)[0]
            proc2 = self._spawn(tmp_path, port2)
            try:
                self._wait_ready(proc2, port2)
                epoch_after = self._digest(port2).get(key, 0)
                assert epoch_after >= epoch_before
                # and it keeps counting from there, never resets
                st, _ = _post(
                    f"127.0.0.1:{port2}", "/index/i/query",
                    b"SetBit(rowID=1, frame=f, columnID=500)")
                assert st == 200
                assert self._digest(port2).get(key, 0) == epoch_after + 1
            finally:
                proc2.kill()
                proc2.communicate(timeout=30)
        finally:
            proc.kill()
            proc.communicate(timeout=30)
