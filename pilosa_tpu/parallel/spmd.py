"""SPMD multi-host serving driver.

In a multi-host `jax.distributed` deployment (connect_distributed,
mesh.py), a compiled collective only runs when EVERY process enters it
with the same program and arguments — an HTTP query landing on one
node cannot unilaterally run a psum over the global mesh. This driver
is the TPU-native answer to the reference's multi-node query fan-out
(executor.go:1103-1163, HTTP RPC per node): rank 0 faces clients,
encodes each device request as a fixed-shape descriptor, broadcasts it
over the device fabric (jax.experimental.multihost_utils), and ALL
processes resolve it against their holder and execute the same
collective. Replication model: the host-side data dir is replicated
across hosts (each process opens the same fragments — the reference's
ReplicaN=N analog); DEVICE memory is what shards, slices spreading
over every host's chips via the global mesh.

Control flow per request:
    rank 0: serve(index, shape, leaves, slices)  -> descriptor
            broadcast_one_to_all(descriptor)     -> all ranks
    all:    decode -> MeshManager._count_args -> compiled collective
    all:    limbs replicated on every process; rank 0 returns the count
Non-zero ranks sit in run_worker() until rank 0 broadcasts a stop.
"""

from __future__ import annotations

import json
from typing import List, Optional, Sequence

import numpy as np

# Fixed descriptor size: broadcast payloads must be identical shapes on
# every rank. 64 KB bounds the slice list of a masked query.
_DESC_BYTES = 65536

_OP_COUNT = 1
_OP_STOP = 2


def _encode(obj: dict) -> np.ndarray:
    raw = json.dumps(obj).encode()
    if len(raw) > _DESC_BYTES:
        raise ValueError(f"descriptor too large: {len(raw)} bytes")
    buf = np.zeros(_DESC_BYTES, dtype=np.uint8)
    buf[: len(raw)] = np.frombuffer(raw, dtype=np.uint8)
    return buf


def _decode(buf: np.ndarray) -> dict:
    raw = bytes(np.asarray(buf, dtype=np.uint8))
    return json.loads(raw[: raw.index(b"\x00")] if b"\x00" in raw else raw)


class SpmdServer:
    """One process's half of the SPMD serving pact.

    Every process constructs this over its own (replicated-data) holder;
    rank 0 calls count(...) per client query, other ranks call
    run_worker() once. All processes must create their MeshManager over
    the same GLOBAL mesh (the default after connect_distributed)."""

    def __init__(self, holder, mesh=None):
        import threading

        import jax

        from .serve import MeshManager

        self.rank = jax.process_index()
        self.manager = MeshManager(holder, mesh=mesh)
        # AOT-compiled programs keyed by (sig, shapes): compilation must
        # happen BEFORE the agreement gate (see _execute), and jit only
        # compiles at first call — lower().compile() forces it eagerly.
        self._compiled: dict = {}
        # Serializes descriptor broadcast + gate + execute: the HTTP
        # front-end is threaded, and two interleaved
        # broadcast_one_to_all collectives from rank 0 would pair
        # nondeterministically with the workers' sequential loop.
        self._mu = threading.Lock()

    # -- rank 0 --------------------------------------------------------------

    def count(self, index: str, shape, leaves: List[tuple],
              slices: Sequence[int], num_slices: int) -> Optional[int]:
        """Broadcast + execute one Count collective. Rank 0 only."""
        assert self.rank == 0, "count() drives from rank 0; others run_worker()"
        desc = {
            "op": _OP_COUNT,
            "index": index,
            "shape": shape,
            "leaves": [list(leaf) for leaf in leaves],
            "slices": list(map(int, slices)),
            "num_slices": int(num_slices),
        }
        with self._mu:
            self._broadcast(desc)
            return self._execute(desc)

    def stop(self):
        """Release every worker loop. Rank 0 only."""
        assert self.rank == 0
        with self._mu:
            self._broadcast({"op": _OP_STOP})

    # -- all ranks -----------------------------------------------------------

    def run_worker(self):
        """Follow rank 0's descriptors until stop. Ranks != 0.

        Errors are contained per descriptor: a raising worker that
        left the loop would wedge every other rank's next collective
        (broadcast_one_to_all blocks until ALL processes enter), so a
        failed execute logs and keeps following."""
        assert self.rank != 0, "rank 0 drives; workers follow"
        while True:
            desc = self._broadcast(None)
            if desc["op"] == _OP_STOP:
                return
            try:
                self._execute(desc)
            except Exception as e:  # noqa: BLE001 — stay in the pact
                import logging

                logging.getLogger("pilosa_tpu.spmd").warning(
                    "spmd worker: descriptor failed: %s", e)

    def _broadcast(self, desc: Optional[dict]) -> dict:
        from jax.experimental import multihost_utils

        payload = _encode(desc) if desc is not None else np.zeros(
            _DESC_BYTES, dtype=np.uint8)
        out = multihost_utils.broadcast_one_to_all(payload)
        return _decode(out)

    def _execute(self, desc: dict) -> Optional[int]:
        """Resolve, AGREE on the program, then execute.

        Resolution can fail — or succeed with a DIFFERENT program — on
        one rank alone (replicated data dirs momentarily out of sync: a
        lagging replica stages a different pool capacity). A rank
        skipping the psum, or entering it with mismatched shapes, hangs
        the whole mesh. So every rank resolves locally, then an
        allgather compares PROGRAM FINGERPRINTS (tree signature + every
        staged array shape, deterministically hashed): the collective
        runs only when every rank resolved the identical program;
        otherwise all skip together."""
        import zlib

        from jax.experimental import multihost_utils

        from .mesh import combine_count

        leaves = [tuple(leaf) for leaf in desc["leaves"]]
        compiled = None
        try:
            prepared = self.manager._count_args(
                desc["index"], desc["shape"], leaves, desc["slices"],
                desc["num_slices"])
            if prepared is not None:
                # Compile BEFORE the gate (jit compiles at first CALL,
                # so force it with AOT lowering): a per-rank compile
                # failure must read as not-ready so every rank skips —
                # compiling after agreement would let warm-cached peers
                # enter the psum while this rank bails.
                # coarse_t (the single-host whole-row fast path) is
                # deliberately unused here: SPMD ranks agree on the
                # GENERAL program, whose eligibility can't diverge
                # between momentarily out-of-sync replicas.
                sig, words_t, idx_t, hit_t, _coarse_t, mask = prepared
                shapes = tuple(
                    [tuple(w.shape) for w in words_t]
                    + [tuple(i.shape) for i in idx_t]
                    + [tuple(mask.shape)])
                ckey = (sig, shapes)
                compiled = self._compiled.get(ckey)
                if compiled is None:
                    fn = self.manager._count_fn(sig, len(idx_t))
                    compiled = fn.lower(words_t, idx_t, hit_t,
                                        mask).compile()
                    self._compiled[ckey] = compiled
        except Exception:  # noqa: BLE001 — counted as not-ready below
            compiled = None
        if compiled is None:
            fp = np.int64(0)
        else:
            blob = json.dumps([sig, list(shapes)]).encode()
            # NOT hash(): Python string hashing is per-process salted.
            fp = np.int64(zlib.crc32(blob) + 1)
        fps = multihost_utils.process_allgather(fp)
        if int(fp) == 0 or not bool(np.all(fps == fps[0])):
            return None  # every rank skips: no divergent collective
        # Past the gate, all ranks run the identical program; a runtime
        # failure here hits every rank symmetrically.
        return combine_count(compiled(words_t, idx_t, hit_t, mask))
