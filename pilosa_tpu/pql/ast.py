"""PQL AST (parity with /root/reference/pql/ast.go).

Arg values carry the parser's Python types: int, float, bool, None, str,
list. `__str__` is the canonical serialization used for remote execution,
so it must round-trip through the parser.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional


# Comparison operators a Cond may carry, in canonical spelling.
COND_OPS = (">", ">=", "<", "<=", "==", "!=", "><")


@dataclass(frozen=True)
class Cond:
    """A value comparison attached to an argument key — the parse of
    `field >= 10` inside Range(frame=f, field >= 10). `op` is one of
    COND_OPS; `value` is an int (or a (low, high) tuple for `><`,
    between, inclusive on both ends). Hashable so Call.cache_key and
    the parse cache keep working."""

    op: str
    value: Any

    def __post_init__(self):
        if self.op not in COND_OPS:
            raise ValueError(f"invalid condition operator {self.op!r}")
        if isinstance(self.value, list):
            object.__setattr__(self, "value", tuple(self.value))

    def __str__(self) -> str:
        return f"{self.op} {_fmt_value(self.value)}"


def _fmt_value(v: Any) -> str:
    if isinstance(v, str):
        return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return "null"
    if isinstance(v, (list, tuple)):
        return "[" + ",".join(_fmt_value(x) if isinstance(x, str) else _fmt_plain(x) for x in v) + "]"
    return _fmt_plain(v)


def _fmt_plain(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        # Positional notation only: the PQL scanner has no exponent
        # syntax, and this string must re-parse on remote nodes.
        s = repr(v)
        if "e" in s or "E" in s:
            s = format(v, ".17f").rstrip("0")
            if s.endswith("."):
                s += "0"
        return s
    return str(v)


@dataclass
class Call:
    name: str
    args: dict = field(default_factory=dict)
    children: list = field(default_factory=list)

    # Sentinel distinguishing "not computed" from a legitimate None key.
    _CKEY_UNSET = object()

    def cache_key(self):
        """Hashable structural identity of this call tree, or None when
        any argument resists hashing (list-valued args become tuples;
        anything stranger declines). Two parses of the same PQL yield
        equal keys, so result caches keyed on it survive re-parsing —
        identity (id()) would only ever hit for a reused Query object.

        Memoized per Call: the walk dominated the warm fast path it
        exists to serve (~56% of a memo-hit Count). Safe because calls
        are immutable after parse by convention — the one site that
        edits args (executor TopN phase 2) edits a fresh clone(),
        which never copies the memo."""
        k = self.__dict__.get("_ckey", self._CKEY_UNSET)
        if k is not self._CKEY_UNSET:
            return k
        k = self._cache_key_uncached()
        self.__dict__["_ckey"] = k
        return k

    @staticmethod
    def _typed(v):
        """Value wrapped with its concrete type: Python equality makes
        1 == 1.0 == True, but Count(rowID=1) and Count(rowID=1.0) are
        DIFFERENT queries (the latter must raise in uint_arg) — a
        type-blind key would let one serve the other from a cache."""
        if isinstance(v, (list, tuple)):
            return tuple(Call._typed(x) for x in v)
        if isinstance(v, Cond):
            return ("Cond", v.op, Call._typed(v.value))
        return (type(v).__name__, v)

    def _cache_key_uncached(self):
        try:
            args = tuple(sorted(
                (k, self._typed(v)) for k, v in self.args.items()))
            hash(args)  # nested unhashables must decline HERE, not
            #             explode later inside a cache's dict probe
            kids = tuple(c.cache_key() for c in self.children)
        except TypeError:
            return None
        if any(k is None for k in kids):
            return None
        return (self.name, args, kids)

    def uint_arg(self, key: str):
        """(value, present). Raises TypeError on a non-integer value
        (reference Call.UintArg, ast.go:52-66)."""
        if key not in self.args:
            return 0, False
        v = self.args[key]
        if isinstance(v, bool) or not isinstance(v, int):
            raise TypeError(f"could not convert {v!r} to uint64 in Call.uint_arg")
        return v & 0xFFFFFFFFFFFFFFFF, True

    def uint_slice_arg(self, key: str):
        """(values, present) for list args (reference UintSliceArg)."""
        if key not in self.args:
            return [], False
        v = self.args[key]
        if not isinstance(v, (list, tuple)) or any(
            isinstance(x, bool) or not isinstance(x, int) for x in v
        ):
            raise TypeError(f"unexpected type in uint_slice_arg, val {v!r}")
        return [x & 0xFFFFFFFFFFFFFFFF for x in v], True

    def keys(self) -> list:
        return sorted(self.args)

    def clone(self) -> "Call":
        return Call(
            name=self.name,
            args=dict(self.args),
            children=[c.clone() for c in self.children],
        )

    def supports_inverse(self) -> bool:
        """Only Bitmap() may target the inverse view (ast.go:174-179)."""
        return self.name == "Bitmap"

    def is_inverse(self, row_label: str, column_label: str) -> bool:
        """True when the call addresses the inverse view: column arg given,
        row arg absent (ast.go:181-195)."""
        if not self.supports_inverse():
            return False
        try:
            _, row_ok = self.uint_arg(row_label)
            _, col_ok = self.uint_arg(column_label)
        except TypeError:
            return False
        return (not row_ok) and col_ok

    def cond_arg(self):
        """The (key, Cond) pair if exactly one argument carries a value
        comparison, else (None, None). More than one comparison in a
        single call is a query error surfaced at execution time."""
        found = [(k, v) for k, v in self.args.items()
                 if isinstance(v, Cond)]
        if len(found) == 1:
            return found[0]
        if len(found) > 1:
            raise ValueError(
                f"{self.name}() supports one field comparison, "
                f"got {len(found)}")
        return None, None

    def __str__(self) -> str:
        parts = [str(c) for c in self.children]
        # Cond-valued args serialize as `key >= 10`, everything else as
        # `key=value` — both re-parse on remote nodes.
        parts += [f"{k} {self.args[k]}" if isinstance(self.args[k], Cond)
                  else f"{k}={_fmt_value(self.args[k])}"
                  for k in self.keys()]
        return f"{self.name or '!UNNAMED'}({', '.join(parts)})"


@dataclass
class Query:
    calls: list = field(default_factory=list)

    def write_call_n(self) -> int:
        """Number of write calls (SetBit/ClearBit/SetValue/Set*Attrs)."""
        return sum(
            1
            for c in self.calls
            if c.name in ("SetBit", "ClearBit", "SetValue",
                          "SetRowAttrs", "SetColumnAttrs")
        )

    def __str__(self) -> str:
        return "\n".join(str(c) for c in self.calls)
