"""Row: a query-level bitmap spanning many slices.

Parity with /root/reference/bitmap.go (the segmented `Bitmap` type): a
sorted map of slice -> slice-local roaring bitmap. Set ops merge
per-slice segments; counts are cached per segment. `attrs` rides along
for query responses (executor.go:218-247).
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

import numpy as np

from .. import SLICE_WIDTH
from ..roaring import Bitmap


class Row:
    """Segmented bitmap over the global column space."""

    __slots__ = ("segments", "attrs", "_counts")

    def __init__(self, columns: Optional[Iterable[int]] = None):
        self.segments: Dict[int, Bitmap] = {}  # slice -> slice-local bitmap
        self.attrs: dict = {}
        self._counts: Dict[int, int] = {}
        if columns is not None:
            for c in columns:
                self.set_bit(int(c))

    # -- construction ------------------------------------------------------

    @classmethod
    def from_segment(cls, slice_: int, bitmap: Bitmap) -> "Row":
        """Wrap one slice-local roaring bitmap (fragment row materialization)."""
        r = cls()
        r.segments[slice_] = bitmap
        return r

    def set_bit(self, column: int) -> bool:
        slice_ = column // SLICE_WIDTH
        seg = self.segments.get(slice_)
        if seg is None:
            seg = self.segments[slice_] = Bitmap()
        self._counts.pop(slice_, None)
        return seg.add(column % SLICE_WIDTH)

    def merge(self, other: "Row") -> None:
        """Union other into self (reference Bitmap.Merge, bitmap.go:45)."""
        for s, seg in other.segments.items():
            mine = self.segments.get(s)
            self.segments[s] = seg.clone() if mine is None else mine.union(seg)
            self._counts.pop(s, None)

    # -- set ops -----------------------------------------------------------

    def _binop(self, other: "Row", op: str, keep_left_only: bool) -> "Row":
        # Pass-through segments are cloned: result Rows must never alias
        # source segments (fragment row caches hand out shared Rows).
        out = Row()
        for s, seg in self.segments.items():
            oseg = other.segments.get(s)
            if oseg is None:
                if keep_left_only:
                    out.segments[s] = seg.clone()
                continue
            merged = getattr(seg, op)(oseg)
            out.segments[s] = merged
        if op in ("union", "xor"):
            for s, oseg in other.segments.items():
                if s not in self.segments:
                    out.segments[s] = oseg.clone()
        out.segments = {s: b for s, b in sorted(out.segments.items())}
        return out

    def intersect(self, other: "Row") -> "Row":
        return self._binop(other, "intersect", keep_left_only=False)

    def union(self, other: "Row") -> "Row":
        return self._binop(other, "union", keep_left_only=True)

    def difference(self, other: "Row") -> "Row":
        return self._binop(other, "difference", keep_left_only=True)

    def xor(self, other: "Row") -> "Row":
        return self._binop(other, "xor", keep_left_only=True)

    def intersection_count(self, other: "Row") -> int:
        total = 0
        for s, seg in self.segments.items():
            oseg = other.segments.get(s)
            if oseg is not None:
                total += seg.intersection_count(oseg)
        return total

    # -- queries -----------------------------------------------------------

    def count(self) -> int:
        total = 0
        for s, seg in self.segments.items():
            n = self._counts.get(s)
            if n is None:
                n = self._counts[s] = seg.count()
            total += n
        return total

    def columns(self) -> np.ndarray:
        """Absolute column IDs, sorted uint64."""
        parts = [
            seg.slice().astype(np.uint64) + np.uint64(s * SLICE_WIDTH)
            for s, seg in sorted(self.segments.items())
        ]
        if not parts:
            return np.empty(0, dtype=np.uint64)
        return np.concatenate(parts)

    def __iter__(self):
        for v in self.columns():
            yield int(v)

    def to_dict(self) -> dict:
        """JSON shape used by the HTTP layer (handler.go bitmap responses)."""
        return {"attrs": self.attrs, "bits": [int(v) for v in self.columns()]}
