"""Fragment: storage + compute unit for one (frame, view, slice).

Parity with /root/reference/fragment.go: owns the durable roaring file
(snapshot region + WAL, snapshot every MAX_OP_N=2000 ops via temp+rename),
an exclusive flock, the TopN count cache with `.cache` persistence,
SHA-1 checksummed 100-row blocks for anti-entropy, and majority-consensus
block merge. The TPU twist: the fragment lazily maintains a device
FragmentPool (pilosa_tpu.ops) as its compute image; host mutations mark
it dirty and it rebuilds on next use.

Bit addressing: pos = rowID * SLICE_WIDTH + (columnID % SLICE_WIDTH)
(reference fragment.go:1511-1514); columnID is absolute, storage is
slice-local.
"""

from __future__ import annotations

import bisect
import fcntl
import functools
import hashlib
import json
import os
import tarfile
import io
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import SLICE_WIDTH, fault
from ..errors import CorruptFragmentError, WriteBackpressureError
from ..obs import StatMap
from ..obs import profile as _profile
from ..obs.log import get_logger
from ..roaring import Bitmap
from ..roaring.serialize import scan_ops
from .cache import CACHE_TYPE_RANKED, DEFAULT_CACHE_SIZE, new_cache
from .row import Row
from .wal import SNAPSHOT_US, WAL_STATS, WalCommitter, WalConfig
from .wal import FSYNC_NEVER as _FSYNC_NEVER

# Snapshot after this many WAL ops (reference fragment.go:62-65).
MAX_OP_N = 2000

# Process-wide integrity counters: corrupt loads detected, read-repairs
# completed, fragments left unrepaired (no replica). Exported as
# pilosa_integrity_* Prometheus families.
INTEGRITY_STATS = StatMap()


class IntegrityContext:
    """Shared data-integrity wiring, threaded Holder→Index→Frame→View→
    Fragment BY REFERENCE (like WalConfig) so the server can inject the
    read-repair source after the cluster client exists and every
    fragment — already-open and future — sees it through the one shared
    object.

    `repair_source(fragment) -> Optional[bytes]` returns a VERIFIED tar
    (write_to_tar format) streamed from a live replica — the server's
    closure fetches via InternalClient.fragment_data and cross-checks
    block checksums against the replica's fragment_blocks before
    handing it over — or None when no replica can supply one."""

    __slots__ = ("repair_source",)

    def __init__(self, repair_source=None):
        self.repair_source = repair_source


def bitmap_block_checksums(bm: Bitmap) -> Dict[int, bytes]:
    """Per-100-row-block SHA-1 digests of a bare bitmap — the same
    hashes Fragment.blocks() serves, computable on a parsed replica
    image or an on-disk snapshot without constructing a Fragment
    (read-repair verification, scrubber disk-vs-memory diff)."""
    out: Dict[int, bytes] = {}
    if not bm.keys:
        return out
    containers_per_block = HASH_BLOCK_SIZE * SLICE_WIDTH >> 16
    for blk in sorted({int(k) // containers_per_block for k in bm.keys}):
        lo = blk * HASH_BLOCK_SIZE * SLICE_WIDTH
        vals = bm.slice_range(lo, lo + HASH_BLOCK_SIZE * SLICE_WIDTH)
        if len(vals) == 0:
            continue
        out[blk] = hashlib.sha1(vals.astype("<u8").tobytes()).digest()
    return out


def bitmap_from_tar(tar_bytes: bytes) -> Optional[Bitmap]:
    """Extract + parse the `data` member of a write_to_tar archive
    (verifying its integrity footer when present)."""
    with tarfile.open(fileobj=io.BytesIO(tar_bytes), mode="r|") as tar:
        for member in tar:
            if member.name == "data":
                buf = tar.extractfile(member).read()
                return Bitmap.from_bytes(buf, verify=True)
    return None


class _MutationEpoch:
    """Process-wide monotonic mutation counter.

    Every completed data mutation that can change a query's answer —
    bit writes, imports/restores (log reset), index/frame create or
    delete, label or time-quantum changes — bumps it. A query-level
    memo validated by `n` (HostQueryCache.query_get) turns a repeated
    read-only Count into one dict probe + one int compare, the host
    analog of the device-side TopN memo.

    Process-wide rather than per-holder on purpose: threading a
    counter through Holder→Index→Frame→View→Fragment buys nothing but
    plumbing — multiple holders share one interpreter only in tests,
    and cross-holder bumps merely over-invalidate (a performance
    non-event), never under-invalidate. The bump is lock-guarded
    because `n += 1` on two threads can lose an update, and a LOST
    bump is the one thing that could validate a stale entry.

    `s` is the STRUCTURAL sub-counter: it moves only when the SET of
    fragments a query could touch — or how its tree lowers — changes
    (fragment/frame/index create or delete, label or time-quantum
    change). Plain bit writes move `n` alone, and pair each bump with
    the touched fragment's own `generation` increment. That split
    lets a query memo that recorded its fragments' generations
    revalidate after an UNRELATED write: `s` unchanged means the
    fragment set is intact, so comparing the recorded generations is
    a complete staleness check (HostQueryCache.query_get)."""

    __slots__ = ("n", "s", "_mu")

    def __init__(self):
        self.n = 0
        self.s = 0
        self._mu = threading.Lock()

    def bump(self):
        with self._mu:
            self.n += 1

    def bump_structural(self):
        with self._mu:
            self.n += 1
            self.s += 1

    def read(self) -> tuple:
        """Consistent (n, s) snapshot. Lock-guarded so a reader racing
        bump_structural can't observe the new `n` with the old `s` —
        a torn pair recorded as a validation stamp would mark state
        validated that the stamping walk never saw."""
        with self._mu:
            return (self.n, self.s)


MUTATION_EPOCH = _MutationEpoch()

# Rows per checksummed block (reference fragment.go HashBlockSize).
HASH_BLOCK_SIZE = 100


class TopOptions:
    """Options for Fragment.top (reference fragment.go TopOptions)."""

    def __init__(self, n=0, src=None, row_ids=None, min_threshold=0,
                 filter_field="", filter_values=None, tanimoto_threshold=0):
        self.n = n
        self.src = src  # Row
        self.row_ids = row_ids or []
        self.min_threshold = min_threshold
        self.filter_field = filter_field
        self.filter_values = filter_values or []
        self.tanimoto_threshold = tanimoto_threshold


def _locked(fn):
    """Run a Fragment method under its reentrant mutex."""
    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._mu:
            return fn(self, *args, **kwargs)
    return wrapper


def _loaded(fn):
    """_locked + demand-load: parse the storage file on first touch.

    The reference gets O(1) fragment open via mmap attach
    (fragment.go:211-229); the host-python analog is lazy parsing — a
    cold server open takes the flock and defers the read, so startup
    on a many-GB data dir is O(schema), and the first query (or the
    background warm thread) pays the parse (SURVEY.md §7 cold-start)."""
    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._mu:
            self.ensure_loaded()
            return fn(self, *args, **kwargs)
    return wrapper


class Fragment:
    """One (frame, view, slice) of data."""

    def __init__(self, path: str, index: str, frame: str, view: str, slice_: int,
                 cache_type: str = CACHE_TYPE_RANKED,
                 cache_size: int = DEFAULT_CACHE_SIZE,
                 row_attr_store=None, stats=None,
                 wal: Optional[WalConfig] = None,
                 integrity: Optional[IntegrityContext] = None):
        self.path = path
        self.index = index
        self.frame = frame
        self.view = view
        self.slice = slice_
        self.cache_type = cache_type
        self.cache_size = cache_size
        self.row_attr_store = row_attr_store
        self.stats = stats
        self.integrity = integrity
        # Wall-clock of the scrubber's last verification pass over this
        # fragment (0 = never scrubbed), for staleness metrics.
        self.last_scrub = 0.0

        # Serializes storage/cache/WAL access across the threaded HTTP
        # server and the executor's per-slice pool (reference
        # Fragment.mu, fragment.go:69). Reentrant: set_bit -> snapshot
        # and top -> row re-enter.
        self._mu = threading.RLock()
        self.storage = Bitmap()
        self.op_n = 0
        # Durability policy ([storage] config). A bare Fragment (tests,
        # embedded use) keeps the historical write-through/no-fsync
        # behavior; server deployments get the config default (group).
        self.wal_cfg = wal if wal is not None else WalConfig(
            fsync_policy=_FSYNC_NEVER)
        self.max_op_n = (self.wal_cfg.max_op_n
                         if self.wal_cfg.max_op_n else MAX_OP_N)
        self._wal = WalCommitter(self.wal_cfg, stats=stats, path=path)
        self.cache = new_cache(cache_type, cache_size)
        self.checksums: Dict[int, bytes] = {}
        # Full blocks() result memo, keyed by mutation generation: the
        # anti-entropy walk, rebalance verification, and the scrubber
        # all hit GET /fragment/blocks repeatedly — an idle fragment
        # answers from this pair instead of re-walking every container.
        self._blocks_gen = -1
        self._blocks_cache: Optional[List[Tuple[int, bytes]]] = None
        self._op_file = None
        self._lock_file = None
        self._pending_load = True
        self._loading = False
        # Non-blocking snapshot state. `_snapshotting` flags a frozen
        # view being written in the background while ops are redirected
        # to the side `.wal` file; `_snap_gen` counts completed
        # attempts (success or failure) so forced-snapshot callers can
        # wait for "a snapshot that started after my mutation".
        self._snapshotting = False
        self._snap_thread: Optional[threading.Thread] = None
        self._snap_done = threading.Event()
        self._snap_done.set()
        self._snap_gen = 0
        self._snap_err: Optional[BaseException] = None
        self._side_file = None
        self._snap_base_op_n = 0
        self._resnap = False
        self._last_snapshot_s = 0.0
        # Materialized-row LRU, bounded: a TopN over a wide row space
        # (or a long-lived server touching many rows) must not pin one
        # Row per row id forever — each cached Row holds its segment
        # arrays. Hits re-rank (move_to_end); inserts evict the LRU
        # entry at the cap.
        self._row_cache: "OrderedDict[int, Row]" = OrderedDict()

        # Device compute image (built lazily; see `pool`).
        self._pool = None
        self._pool_row_ids = None
        self._pool_dirty = True
        self._pool_keys_host = None
        self._pool_gen = 0

        # Mutation log for incremental device-image maintenance: device
        # consumers (the fragment's own pool, the mesh serving layer)
        # record the generation they staged at and later ask
        # log_since(gen) for the bits written since — applying them as a
        # device scatter instead of re-uploading the whole pool
        # (SURVEY.md §7 "mutation on device": host-buffered batches,
        # device scatter). Entries: (op 0=set/1=clear, pos, churn) where
        # churn means the container SET changed (new container created /
        # emptied container removed) — a churned pool must rebuild, a
        # scatter can't add or drop key slots.
        self.generation = 0
        self._log: List[Tuple[int, int, bool]] = []
        self._log_base = 0
        self._log_limit = 8192

        # Replication epoch (ISSUE 18): a monotonic count of mutations
        # applied to THIS replica of the fragment, comparable across
        # replicas because every write fans out to all owners and each
        # bumps once per op — a replica whose epoch trails the max is
        # exactly that many writes behind. Durability rides a tiny
        # sidecar file (`<path>.epoch`) holding a BASE such that
        # epoch = base + op_n at load; the base is rewritten at the
        # points where op_n's meaning changes (snapshot freeze, clean
        # close, floor-raise). Crash windows can only OVER-state the
        # reloaded epoch (the sidecar lands before the snapshot
        # rename), never regress it — an overshoot merely invalidates
        # caches early, a regression would serve stale ones.
        self.epoch = 0
        self._snap_epoch_base = 0

    # -- lifecycle ---------------------------------------------------------

    @property
    def cache_path(self) -> str:
        return self.path + ".cache"

    @property
    def epoch_path(self) -> str:
        return self.path + ".epoch"

    def _read_epoch_base(self) -> int:
        """The persisted sidecar base (0 when absent/unreadable —
        pre-epoch data starts counting from its parsed op count)."""
        try:
            with open(self.epoch_path, "rb") as f:
                return max(0, int(f.read().decode().strip() or "0"))
        except (OSError, ValueError):
            return 0

    def _write_epoch_base(self, base: int) -> None:
        """Durably persist the sidecar base (tmp + fsync + rename, the
        snapshot idiom — a torn sidecar must never parse as a smaller
        number). Max-merged with the current sidecar: the base is
        monotone over a fragment's life (epoch only grows, and op_n
        never outruns the bumps it contributed), so taking the max
        makes the snapshot worker and a concurrent floor-raise
        commutative. Best-effort: a failed write only costs exactness
        at the next load, and the load-time fallback over-states."""
        base = max(int(base), self._read_epoch_base())
        tmp = self.epoch_path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                f.write(str(base).encode())
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.epoch_path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def advance_epoch(self, to: int) -> int:
        """Floor-raise the replication epoch to at least `to` (anti-
        entropy / hint-replay reconcile: a replica that converged by
        block merge may have bumped fewer times than the origin —
        equalizing the counters keeps cross-replica digests comparable).
        Never regresses; persists the new base eagerly so a restart
        cannot fall back below the reconciled floor. Returns the
        resulting epoch."""
        with self._mu:
            self.ensure_loaded()
            to = int(to)
            if to <= self.epoch:
                return self.epoch
            delta = to - self.epoch
            self.epoch = to
            if self._snapshotting:
                # The in-flight worker will persist _snap_epoch_base at
                # rename; carry the raise so the reload can't fall
                # below the reconciled floor.
                self._snap_epoch_base += delta
            self._write_epoch_base(self.epoch - self.op_n)
            return self.epoch

    @_locked
    def open(self, lazy: bool = False):
        """Acquire the flock; parse now, or on first touch when `lazy`
        (the holder's directory scan opens every fragment lazily so a
        cold start is O(schema), not O(data))."""
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        # Exclusive advisory lock (reference fragment.go:191).
        self._lock_file = open(self.path + ".lock", "w")
        try:
            fcntl.flock(self._lock_file, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            self._lock_file.close()
            self._lock_file = None
            raise RuntimeError(f"fragment locked by another process: {self.path}")
        if lazy and os.path.exists(self.path) and os.path.getsize(self.path) > 0:
            self._pending_load = True
            return
        self.ensure_loaded()

    def ensure_loaded(self):
        """Parse the storage file + attach the WAL + load the cache if
        not yet done. Callers hold _mu (all public paths do).

        _pending_load clears only on FULL success: a corrupt file must
        raise on every touch, never leave the fragment looking loaded-
        but-empty — acked writes would miss the WAL and the next
        snapshot would overwrite the real data with the empty image.
        The separate _loading flag breaks the _load_cache →
        rebuild_cache → row() re-entry, not the retry.

        A storage image that fails integrity verification (footer CRC
        mismatch, rotted header, mid-log op corruption) does NOT
        crash-loop: the rotted file is quarantined aside and the
        fragment read-repairs from a live replica via the injected
        IntegrityContext.repair_source, all under _mu — concurrent
        queries block on the lock and then see the repaired image.
        Only when no replica can supply a verified copy does the touch
        raise CorruptFragmentError (a SliceUnavailableError, so the
        executor re-splits / degrades to partial), and the NEXT touch
        retries the repair."""
        if not self._pending_load or self._loading:
            return
        self._loading = True
        try:
            try:
                self._load_storage()
            except ValueError as err:
                self._recover_corrupt(err)
            self._load_cache()
            self._pending_load = False
        finally:
            self._loading = False

    def _load_storage(self):
        """Read + verify + parse the storage file, attach the append
        fd, and replay any side WAL. Raises ValueError (incl.
        CorruptSnapshotError) on a rotted image, with no append fd left
        attached."""
        if self._op_file is not None:
            # Retry after a failed attempt: drop the stale fd first.
            self.storage.op_writer = None
            try:
                self._op_file.close()
            except OSError:
                pass
            self._op_file = None
        if os.path.exists(self.path) and os.path.getsize(self.path) > 0:
            with open(self.path, "rb") as f:
                data = f.read()
            data = fault.corrupt("storage.corrupt", data, path=self.path,
                                 kind="snapshot")
            self.storage = Bitmap.from_bytes(data, truncate_torn_tail=True,
                                             verify=True)
            self.op_n = self.storage.op_n
            torn = self.storage.torn_tail_bytes
            if torn:
                # Crash mid-append left a damaged final op. The
                # acknowledged prefix is intact — drop the tail on
                # disk BEFORE attaching the append fd, or the next
                # replay would see the garbage mid-log and refuse
                # to load (kill -9 recovery, ISSUE 7 satellite).
                WAL_STATS.inc("torn_tails")
                get_logger("pilosa.fragment").warning(
                    "torn WAL tail: truncating %d trailing bytes "
                    "of %s (crash recovery)", torn, self.path)
                os.truncate(self.path, len(data) - torn)
        else:
            with open(self.path, "wb") as f:
                self.storage.write_to(f, footer=True)
        # Unbuffered append fd; ops route through the per-fragment
        # WAL committer, which write-throughs (fsync-policy never)
        # or group-commits (group/always) per [storage] config.
        self._op_file = open(self.path, "ab", buffering=0)
        self._wal.retarget(self._op_file)
        self.storage.op_writer = self._wal
        try:
            self._replay_side_wal()
        except ValueError:
            # Rotted side WAL: detach before recovery quarantines it.
            self.storage.op_writer = None
            try:
                self._op_file.close()
            except OSError:
                pass
            self._op_file = None
            raise
        # Replication epoch restore: sidecar base + every op parsed
        # beyond the snapshot region (side-WAL replay included — those
        # ops bumped the epoch before the crash). Floor-merged with any
        # in-memory value so a reload can only advance it.
        self.epoch = max(self.epoch, self._read_epoch_base() + self.op_n)

    def _recover_corrupt(self, err: BaseException):
        """Corrupt-storage recovery: stream a verified replica copy
        through the rebalance transfer format and swap it in. Caller is
        ensure_loaded, under _mu with _loading set.

        Ordering is the safety property: the rotted file is moved aside
        (as `.corrupt` evidence) only AFTER a verified replacement is
        in hand. An unrepaired fragment keeps the rot in place so every
        retry re-detects it and raises — it must never degrade to a
        fresh empty image whose next snapshot would bury the real data."""
        INTEGRITY_STATS.inc("corrupt")
        if self.stats:
            self.stats.count("corruptN", 1)
        log = get_logger("pilosa.fragment")
        log.error(
            "corrupt fragment storage %s (%s/%s/%d): %s — attempting "
            "read-repair from a replica", self.path, self.frame,
            self.view, self.slice, err)
        self.storage = Bitmap()  # drop any partially-parsed image
        self.op_n = 0
        bm = None
        src = self.integrity.repair_source if self.integrity else None
        if src is not None:
            try:
                tar_bytes = src(self)
                if tar_bytes:
                    bm = bitmap_from_tar(tar_bytes)
            except Exception as rerr:  # noqa: BLE001 — degrade, not crash
                log.error("read-repair of %s failed: %s", self.path, rerr)
        if bm is None:
            INTEGRITY_STATS.inc("unrepaired")
            raise CorruptFragmentError(
                f"fragment {self.frame}/{self.view}/{self.slice} is "
                f"corrupt and no replica supplied a verified copy: "
                f"{err}") from err
        if os.path.exists(self.path):
            try:
                os.replace(self.path, self.path + ".corrupt")
            except OSError:
                pass
        tmp = self.path + ".snapshotting"
        with open(tmp, "wb") as f:
            bm.write_to(f, footer=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        # Attach directly off the parsed image — re-reading through
        # _load_storage would run the freshly-written bytes back
        # through the bit-rot seam and re-detect an injected fault.
        self.storage = bm
        self.op_n = bm.op_n
        self._op_file = open(self.path, "ab", buffering=0)
        self._wal.retarget(self._op_file)
        self.storage.op_writer = self._wal
        try:
            # Locally-acked ops stranded in a side WAL survive the
            # repair: absolute positions replay idempotently onto the
            # replica image.
            self._replay_side_wal()
        except ValueError as serr:
            side_path = self.path + ".wal"
            log.error("side WAL of repaired fragment %s is also rotted "
                      "(%s): quarantined, anti-entropy will reconverge",
                      self.path, serr)
            try:
                os.replace(side_path, side_path + ".corrupt")
            except OSError:
                pass
        # Repaired state is at least as new as whatever the sidecar
        # covered; the _mark_dirty reset below bumps once more so every
        # epoch-keyed cache over this fragment invalidates.
        self.epoch = max(self.epoch, self._read_epoch_base() + self.op_n)
        self._mark_dirty(None)  # device pools/caches rebuild from scratch
        self._write_epoch_base(self.epoch - self.op_n)
        INTEGRITY_STATS.inc("repaired")
        log.warning("read-repair: %s (%s/%s/%d) restored from replica",
                    self.path, self.frame, self.view, self.slice)

    def _replay_side_wal(self):
        """Crash recovery for a background snapshot that died mid-way:
        a leftover side `.wal` file holds every op accepted after the
        snapshot's freeze point. Replay it onto the loaded image and
        splice its bytes into the main file (append + fsync BEFORE
        unlinking — dropping the side file first would lose acked ops
        to a crash in between). Ops are absolute positions, so replay
        is idempotent whether the main file is the pre-crash original
        (rename never happened) or the renamed snapshot — and even if
        a previous splice appended but didn't unlink."""
        tmp = self.path + ".snapshotting"
        if os.path.exists(tmp):
            # Snapshot temp never renamed: dead weight.
            os.unlink(tmp)
        side_path = self.path + ".wal"
        if not os.path.exists(side_path):
            return
        with open(side_path, "rb") as f:
            data = f.read()
        data = fault.corrupt("storage.corrupt", data, path=side_path,
                             kind="side-wal")
        ops, valid, torn = scan_ops(data)
        if torn:
            WAL_STATS.inc("torn_tails")
            get_logger("pilosa.fragment").warning(
                "torn side-WAL tail: dropping %d trailing bytes of %s "
                "(crash recovery)", torn, side_path)
        for typ, value in ops:
            if typ == 0:
                self.storage._add_one(value)
            else:
                self.storage._remove_one(value)
        if valid:
            self._op_file.write(data[:valid])
            os.fsync(self._op_file.fileno())
        os.unlink(side_path)
        self.op_n += len(ops)
        self.storage.op_n = self.op_n
        if ops:
            get_logger("pilosa.fragment").info(
                "replayed %d side-WAL ops into %s (crash recovery)",
                len(ops), self.path)

    def close(self):
        # Drain any in-flight background snapshot (and chained
        # re-snapshot) BEFORE tearing down fds. Joined outside _mu:
        # the worker's finish step needs the fragment lock.
        while True:
            with self._mu:
                t = self._snap_thread if self._snapshotting else None
            if t is None:
                break
            t.join()
        with self._mu:
            self.flush_cache()
            # Flush + release barrier waiters; pending buffered ops
            # reach disk (fsynced under a syncing policy).
            self._wal.detach()
            if self._op_file is not None:
                self._op_file.close()
                self._op_file = None
            self.storage.op_writer = None
            if self._lock_file is not None:
                fcntl.flock(self._lock_file, fcntl.LOCK_UN)
                self._lock_file.close()
                self._lock_file = None
            # Clean close: persist the exact epoch base (a loaded
            # fragment only — an untouched lazy fragment has nothing
            # truer than the sidecar already on disk).
            if not self._pending_load:
                self._write_epoch_base(self.epoch - self.op_n)
            # A reopened fragment must re-parse and re-attach the WAL —
            # a stale loaded flag would leave op_writer detached and
            # silently drop acked writes on the floor.
            self._pending_load = True

    # -- reads -------------------------------------------------------------

    # Bound on materialized rows held by _row_cache (see __init__).
    _ROW_CACHE_MAX = 512

    @_loaded
    def row(self, row_id: int) -> Row:
        """Materialize one row as a slice-local segment (fragment.go:332-367)."""
        cached = self._row_cache.get(row_id)
        if cached is not None:
            self._row_cache.move_to_end(row_id)  # LRU, not FIFO
            return cached
        seg = self.storage.offset_range(
            0, row_id * SLICE_WIDTH, (row_id + 1) * SLICE_WIDTH
        )
        r = Row.from_segment(self.slice, seg)
        if len(self._row_cache) >= self._ROW_CACHE_MAX:
            self._row_cache.popitem(last=False)
        self._row_cache[row_id] = r
        return r

    @_loaded
    def count(self) -> int:
        return self.storage.count()

    @_loaded
    def max_row_id(self) -> int:
        return self.storage.max() // SLICE_WIDTH

    def for_each_bit(self):
        """Yield (rowID, absolute columnID) pairs (fragment.go:471-488).

        Snapshots the positions under the mutex first — decorating a
        generator would release the lock before iteration starts, and
        concurrent writers mutate the container lists mid-walk."""
        base = self.slice * SLICE_WIDTH
        with self._mu:
            self.ensure_loaded()
            positions = self.storage.slice()
        for pos in positions:
            pos = int(pos)
            yield pos // SLICE_WIDTH, base + (pos % SLICE_WIDTH)

    # -- writes ------------------------------------------------------------

    def _pos(self, row_id: int, column_id: int) -> int:
        return row_id * SLICE_WIDTH + (column_id % SLICE_WIDTH)

    def set_bit(self, row_id: int, column_id: int,
                deadline: Optional[float] = None) -> bool:
        """Set a bit; WAL-append, update caches, wait the durability
        barrier. Returns True if the bit was newly set
        (fragment.go:371-413). `deadline` (absolute monotonic, from
        the query's ExecOptions) caps any backpressure wait."""
        self._wal_gate(deadline)
        with self._mu:
            self.ensure_loaded()
            pos = self._pos(row_id, column_id)
            churn = self.storage._find_key(pos >> 16) < 0
            changed = self.storage.add(pos)
            seq = self._wal.seq()
            self._log_append(0, pos, churn)
            self._mark_dirty(row_id)
            if changed:
                # Row-cache update happens BEFORE the snapshot trigger
                # (and the trigger itself is now only an async flip), so
                # a max_op_n=1 fragment never recounts a row mid-
                # snapshot-churn.
                self.cache.add(row_id, self.row(row_id).count())
                if self.stats:
                    self.stats.count("setN", 1)
            self._increment_op_n()
        with _profile.phase("wal_commit"):
            self._wal.wait_durable(seq)
        return changed

    def clear_bit(self, row_id: int, column_id: int,
                  deadline: Optional[float] = None) -> bool:
        self._wal_gate(deadline)
        with self._mu:
            self.ensure_loaded()
            pos = self._pos(row_id, column_id)
            changed = self.storage.remove(pos)
            seq = self._wal.seq()
            churn = changed and self.storage._find_key(pos >> 16) < 0
            self._log_append(1, pos, churn)
            self._mark_dirty(row_id)
            if changed:
                self.cache.add(row_id, self.row(row_id).count())
                if self.stats:
                    self.stats.count("clearN", 1)
            self._increment_op_n()
        with _profile.phase("wal_commit"):
            self._wal.wait_durable(seq)
        return changed

    def _pending_wal_ops(self) -> int:
        """Ops not yet covered by a completed or in-flight-frozen
        snapshot — the quantity [storage] max-wal-ops bounds. During a
        background snapshot that's the side-WAL op count; otherwise
        the whole un-snapshotted log."""
        if self._snapshotting:
            return self.op_n - self._snap_base_op_n
        return self.op_n

    def _wal_gate(self, deadline: Optional[float] = None):
        """Write backpressure: when the snapshot falls behind sustained
        ingest and the pending WAL outgrows max-wal-ops, block the
        writer (outside _mu — readers keep serving) until a snapshot
        lands or the deadline expires, then shed with
        WriteBackpressureError (HTTP 503 + Retry-After)."""
        limit = self.wal_cfg.max_wal_ops
        if limit <= 0 or self._pending_load:
            return
        if self._mu._is_owned():
            # Reentrant write (consensus merge holding _mu): blocking
            # here could never make progress — the snapshot's finish
            # step needs the lock this thread already holds.
            return
        # Unlocked int reads: the bound is advisory within one op.
        if self._pending_wal_ops() <= limit:
            return
        WAL_STATS.inc("backpressure")
        if self.stats:
            self.stats.count("wal_backpressureN", 1)
        give_up = time.monotonic() + self.wal_cfg.backpressure_deadline
        if deadline is not None:
            give_up = min(give_up, deadline)
        while True:
            with self._mu:
                if self._pending_wal_ops() <= limit:
                    return
                if not self._snapshotting:
                    self._start_snapshot()
                done = self._snap_done
            remaining = give_up - time.monotonic()
            if remaining <= 0:
                WAL_STATS.inc("backpressure_shed")
                if self.stats:
                    self.stats.count("wal_shedN", 1)
                retry = max(1.0, self._last_snapshot_s or 1.0)
                raise WriteBackpressureError(
                    f"write backpressure: {self._pending_wal_ops()} "
                    f"pending WAL ops > max-wal-ops={limit} on "
                    f"{self.frame}/{self.view}/{self.slice}",
                    retry_after_s=retry)
            done.wait(min(remaining, 0.05))

    # -- mutation log (device-image maintenance) -----------------------------

    def _log_append(self, op: int, pos: int, churn: bool):
        self.generation += 1
        self.epoch += 1
        MUTATION_EPOCH.bump()
        self._log.append((op, pos, churn))
        if len(self._log) > self._log_limit:
            drop = len(self._log) - self._log_limit
            del self._log[:drop]
            self._log_base += drop

    def _log_reset(self):
        """Wholesale storage replacement (import, restore): consumers at
        any earlier generation must rebuild."""
        self.generation += 1
        self.epoch += 1
        MUTATION_EPOCH.bump()
        self._log.clear()
        self._log_base = self.generation

    @_locked
    def log_since(self, gen: int) -> Optional[List[Tuple[int, int, bool]]]:
        """Mutations after generation `gen`, or None when the log no
        longer reaches back that far (pruned/reset → rebuild)."""
        if gen < self._log_base or gen > self.generation:
            return None
        return self._log[gen - self._log_base:]

    def _mark_dirty(self, row_id: Optional[int]):
        self._pool_dirty = True
        if row_id is None:
            self._log_reset()
        self.checksums.pop(
            -1 if row_id is None else row_id // HASH_BLOCK_SIZE, None
        )
        if row_id is None:
            self.checksums.clear()
            self._row_cache.clear()
        else:
            self._row_cache.pop(row_id, None)

    def _increment_op_n(self):
        self.op_n += 1
        if self.op_n > self.max_op_n and not self._snapshotting:
            # Async flip only — the writer never waits for the rewrite.
            self._start_snapshot()

    def import_bits(self, row_ids: Sequence[int], column_ids: Sequence[int]):
        """Bulk import: WAL-detached adds + forced snapshot
        (fragment.go:922-989). Goes through the non-blocking snapshot
        engine but WAITS for it to land — a bulk import's ops have no
        WAL records, so its commit barrier IS the snapshot. Concurrent
        readers and per-bit writers on other rows keep serving
        throughout (the rewrite happens off a frozen view)."""
        rows = np.asarray(row_ids, dtype=np.uint64)
        cols = np.asarray(column_ids, dtype=np.uint64)
        if rows.shape != cols.shape:
            raise ValueError("row/column mismatch")
        pos = rows * np.uint64(SLICE_WIDTH) + (cols % np.uint64(SLICE_WIDTH))
        while True:
            # Apply only when a covering snapshot can start at once: a
            # freeze taken BEFORE the bulk apply would persist a state
            # the import's barrier doesn't cover.
            with self._mu:
                self.ensure_loaded()
                if not self._snapshotting:
                    self._import_apply_locked(rows, pos)
                    target = self._snap_gen + 1
                    self._start_snapshot()
                    break
                done = self._snap_done
            done.wait()
        self._await_snapshot(target)

    def _import_apply_locked(self, rows: np.ndarray, pos: np.ndarray):
        """In-memory bulk apply. On ANY failure after partial mutation
        the fragment reloads from disk — bulk ops write no WAL records,
        so the on-disk state is still the consistent pre-import image
        and re-parsing it restores memory to match (the alternative,
        snapshotting a partially-applied import, would silently persist
        half a bulk load)."""
        self.storage.op_writer = None
        try:
            self.storage.add_many(pos)
            fault.point("storage.import_apply", path=self.path)
            self._mark_dirty(None)
            for r in np.unique(rows):
                self.cache.bulk_add(int(r), self.row(int(r)).count())
            self.cache.invalidate()
        except BaseException:
            self._reload_from_disk()
            raise
        finally:
            self.storage.op_writer = self._wal

    def _reload_from_disk(self):
        """Discard the in-memory image and re-parse the on-disk state
        (failed bulk-import recovery). The append fd and flock stay as
        they are; buffered WAL ops are flushed first so the file covers
        every accepted per-bit op."""
        self._wal.flush()
        with open(self.path, "rb") as f:
            data = f.read()
        self.storage = Bitmap.from_bytes(data)
        self.op_n = self.storage.op_n
        self.storage.op_writer = self._wal
        self._mark_dirty(None)
        self.cache = new_cache(self.cache_type, self.cache_size)
        self.rebuild_cache()

    # -- non-blocking snapshots ----------------------------------------------

    def snapshot(self):
        """Force a snapshot covering the current state and wait for it
        to land (temp + fsync + rename, spliced side WAL). Raises the
        background writer's error, if any — with the fragment left
        fully serviceable either way (the op writer is never detached;
        a failed attempt drains the side WAL back into the still-valid
        main file)."""
        with self._mu:
            self.ensure_loaded()
            target = self._request_snapshot_locked()
        self._await_snapshot(target)

    def wait_snapshot(self, timeout: Optional[float] = None) -> bool:
        """Block until no snapshot is in flight (tests/operators).
        Returns False on timeout."""
        give_up = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._mu:
                if not self._snapshotting:
                    return True
                done = self._snap_done
            left = None if give_up is None else give_up - time.monotonic()
            if left is not None and left <= 0:
                return False
            done.wait(left)

    def storage_state(self) -> dict:
        """Durability/snapshot state for /debug/vars (unlocked reads:
        a racing writer skews a counter by one, never tears)."""
        return {
            "op_n": self.op_n,
            "epoch": self.epoch,
            "max_op_n": self.max_op_n,
            "pending_wal_ops": self._pending_wal_ops(),
            "snapshotting": self._snapshotting,
            "fsync_policy": self.wal_cfg.fsync_policy,
            "wal_fsyncs": self._wal.fsyncs,
            "last_snapshot_ms": round(self._last_snapshot_s * 1e3, 3),
        }

    def _request_snapshot_locked(self) -> int:
        """Ensure a snapshot covering the CURRENT storage state will
        run; returns the generation to wait for. If one is already in
        flight its freeze predates us, so chain another behind it."""
        if self._snapshotting:
            self._resnap = True
            return self._snap_gen + 2
        self._start_snapshot()
        return self._snap_gen + 1

    def _start_snapshot(self):
        """The redirect flip (holds _mu, cost O(containers) + one
        fsync): freeze the storage view, aim the committer at a fresh
        side `.wal` file, and hand the frozen image to a background
        writer. This is the only stall a writer ever pays for a
        snapshot."""
        frozen = self.storage.freeze_view()
        self._side_file = open(self.path + ".wal", "wb", buffering=0)
        # Drains + fsyncs pending ops into the main file first, so the
        # main/side split is exactly at the freeze point.
        self._wal.retarget(self._side_file)
        self._snap_base_op_n = self.op_n
        # Epoch base the landed snapshot will persist: everything up to
        # the freeze is folded into the snapshot region, so on reload
        # epoch = this base + the (side) ops parsed beyond it.
        self._snap_epoch_base = self.epoch
        self._snapshotting = True
        self._snap_done = threading.Event()
        self._snap_thread = threading.Thread(
            target=self._snapshot_worker, args=(frozen,),
            name=f"snapshot:{self.frame}/{self.view}/{self.slice}",
            daemon=True)
        self._snap_thread.start()

    def _snapshot_worker(self, frozen: Bitmap):
        from ..obs.health import HEALTH

        start = time.monotonic()
        err: Optional[BaseException] = None
        tmp = self.path + ".snapshotting"
        try:
            # Visibility-only bracket (base=None): snapshot wall time
            # scales with fragment size so the watchdog never judges
            # it, but a disk-wedged snapshot shows up in /debug/health
            # with this thread's name and stack.
            with HEALTH.inflight("snapshot", "write"), \
                    open(tmp, "wb") as f:
                # Integrity footer rides the temp through the atomic
                # rename: every durable snapshot is born verifiable.
                frozen.write_to(f, footer=True)
                f.flush()
                fault.point("storage.fsync", path=self.path,
                            kind="snapshot")
                os.fsync(f.fileno())
            fault.point("storage.rename", path=self.path)
            # Sidecar BEFORE the rename: a crash between the two leaves
            # the new base paired with the OLD (op-richer) file, which
            # can only over-state the reloaded epoch — the safe
            # direction. The reverse order could pair the new snapshot
            # (op_n reset) with the old base and regress it.
            self._write_epoch_base(self._snap_epoch_base)
            os.replace(tmp, self.path)
        except BaseException as e:  # noqa: BLE001 — must reach _finish
            err = e
            try:
                os.unlink(tmp)
            except OSError:
                pass
        self._finish_snapshot(err, start)

    def _finish_snapshot(self, err: Optional[BaseException], start: float):
        """Splice (briefly under _mu): drain the side WAL into the new
        main file — or, on a failed attempt, back into the still-valid
        old one — reattach the committer, and wake waiters. The side
        file is unlinked only AFTER its bytes are durable in main."""
        with self._mu:
            try:
                side_path = self.path + ".wal"
                if err is None:
                    target = open(self.path, "ab", buffering=0)
                else:
                    target = self._op_file
                # Flushes buffered ops into the side file (fsynced
                # under a syncing policy), then aims appends at main.
                self._wal.retarget(target)
                self._side_file.close()
                self._side_file = None
                with open(side_path, "rb") as sf:
                    side_bytes = sf.read()
                if side_bytes:
                    target.write(side_bytes)
                    if self.wal_cfg.fsync_policy != _FSYNC_NEVER:
                        os.fsync(target.fileno())
                os.unlink(side_path)
                if err is None:
                    self._op_file.close()
                    self._op_file = target
                    self.op_n -= self._snap_base_op_n
                    self.storage.op_n = self.op_n
                # On failure op_n keeps counting from the last real
                # snapshot; the next trigger retries the whole flip.
            finally:
                elapsed = time.monotonic() - start
                self._last_snapshot_s = elapsed
                self._snap_err = err
                self._snap_gen += 1
                self._snapshotting = False
                self._snap_thread = None
                resnap, self._resnap = self._resnap, False
                # Capture THIS attempt's event before a chained
                # re-snapshot replaces _snap_done with a fresh one —
                # waiters parked on the old event must still wake.
                done_evt = self._snap_done
                if resnap:
                    self._start_snapshot()
                done_evt.set()
        SNAPSHOT_US.observe(int(elapsed * 1e6))
        WAL_STATS.inc("snapshots_failed" if err else "snapshots")
        if self.stats:
            self.stats.timing("snapshot_us", int(elapsed * 1e6))
        if err is not None:
            get_logger("fragment").warning(
                "snapshot failed: %s (%s/%s/%d): %s — side WAL drained "
                "back into main, will retry",
                self.path, self.frame, self.view, self.slice, err)
        elif elapsed > 0.1:
            # Slow-snapshot visibility (the reference's track() logging,
            # fragment.go:1012-1020) — now background wall time, not a
            # write stall a client felt.
            get_logger("fragment").info(
                "slow snapshot: %s (%s/%s/%d) took %.0f ms (background)",
                self.path, self.frame, self.view, self.slice,
                elapsed * 1e3)

    def _await_snapshot(self, target_gen: int):
        """Wait (WITHOUT holding _mu — the worker's finish step needs
        it) until `target_gen` snapshots have completed; raise the
        covering attempt's error."""
        while True:
            with self._mu:
                if self._snap_gen >= target_gen:
                    err = self._snap_err
                    break
                done = self._snap_done
            done.wait()
        if err is not None:
            raise err

    # -- TopN ---------------------------------------------------------------

    def _top_pairs(self, row_ids: Sequence[int]) -> List[Tuple[int, int]]:
        """Reference topBitmapPairs (fragment.go:627-658): rank cache when
        no ids requested; otherwise exact per-id counts, zeros dropped,
        sorted desc. Deviation: requested ids always recount from storage
        — the reference trusts cache.Get first, but threshold-gated
        RankCache.add never records drops to zero, so a cleared row would
        keep its stale count and poison TopN's exact phase 2."""
        if not row_ids:
            # cache.top() recalculates when dirty; no invalidate() needed.
            return self.cache.top()
        pairs = [(r, self.row(r).count()) for r in row_ids]
        pairs = [(r, n) for r, n in pairs if n > 0]
        pairs.sort(key=lambda p: (-p[1], p[0]))
        return pairs

    @_loaded
    def top(self, opt: TopOptions) -> List[Tuple[int, int]]:
        """Top rows by count (reference fragment.go:493-625), including
        src-intersection recount, min-threshold, attr filters, and the
        Tanimoto band."""
        pairs = self._top_pairs(opt.row_ids)
        n = 0 if opt.row_ids else opt.n

        filters = set(opt.filter_values) if (opt.filter_field and opt.filter_values) else None

        tanimoto = 0
        min_tan = max_tan = 0.0
        src_count = 0
        if opt.tanimoto_threshold > 0 and opt.src is not None:
            tanimoto = opt.tanimoto_threshold
            src_count = opt.src.count()
            min_tan = src_count * tanimoto / 100.0
            max_tan = src_count * 100.0 / tanimoto

        results: List[Tuple[int, int]] = []  # kept sorted desc by count

        def push(pair):
            bisect.insort(results, pair, key=lambda p: (-p[1], p[0]))

        for row_id, cnt in pairs:
            if cnt <= 0:
                continue
            if tanimoto > 0:
                if cnt <= min_tan or cnt >= max_tan:
                    continue
            elif cnt < opt.min_threshold:
                continue
            if filters is not None:
                if self.row_attr_store is None:
                    continue
                attr = self.row_attr_store.attrs(row_id)
                if not attr or attr.get(opt.filter_field) not in filters:
                    continue

            if n == 0 or len(results) < n:
                count = cnt
                if opt.src is not None:
                    count = opt.src.intersection_count(self.row(row_id))
                if count == 0:
                    continue
                if tanimoto > 0:
                    t = -(-100 * count // (cnt + src_count - count))  # ceil
                    if t <= tanimoto:
                        continue
                elif count < opt.min_threshold:
                    continue
                push((row_id, count))
                if n > 0 and len(results) == n and opt.src is None:
                    break
                continue

            threshold = results[-1][1]
            if threshold < opt.min_threshold or cnt < threshold:
                break
            count = opt.src.intersection_count(self.row(row_id))
            if count < threshold:
                continue
            push((row_id, count))
            results[:] = results[:n] if n else results

        return results[:n] if n else results

    # -- block checksums / anti-entropy -------------------------------------

    def _block_of(self, pos: int) -> int:
        return pos // (HASH_BLOCK_SIZE * SLICE_WIDTH)

    @_loaded
    def blocks(self) -> List[Tuple[int, bytes]]:
        """[(block_id, sha1)] for all non-empty 100-row blocks
        (fragment.go:703-767). Only blocks with live containers are
        visited — a 100-row block spans exactly 1600 containers, so
        candidate block ids come straight from the container keys (a
        sparse huge-rowID fragment must not scan the dense block range).
        Checksums are cached per block and invalidated by writes; on
        top of that the WHOLE result list is memoized per mutation
        generation, so back-to-back anti-entropy / rebalance / scrub
        passes over an idle fragment cost one int compare instead of a
        container-key walk."""
        if self._blocks_cache is not None \
                and self._blocks_gen == self.generation:
            return list(self._blocks_cache)
        out: List[Tuple[int, bytes]] = []
        if not self.storage.keys:
            self._blocks_cache = []
            self._blocks_gen = self.generation
            return out
        containers_per_block = HASH_BLOCK_SIZE * SLICE_WIDTH >> 16
        for blk in sorted({int(k) // containers_per_block for k in self.storage.keys}):
            cached = self.checksums.get(blk)
            if cached is not None:
                out.append((blk, cached))
                continue
            lo = blk * HASH_BLOCK_SIZE * SLICE_WIDTH
            vals = self.storage.slice_range(lo, lo + HASH_BLOCK_SIZE * SLICE_WIDTH)
            if len(vals) == 0:
                continue
            digest = hashlib.sha1(vals.astype("<u8").tobytes()).digest()
            self.checksums[blk] = digest
            out.append((blk, digest))
        self._blocks_cache = list(out)
        self._blocks_gen = self.generation
        return out

    @_loaded
    def checksum(self) -> bytes:
        h = hashlib.sha1()
        for _, c in self.blocks():
            h.update(c)
        return h.digest()

    @_loaded
    def block_data(self, block_id: int) -> Tuple[np.ndarray, np.ndarray]:
        """(rowIDs, slice-local columnIDs) for one block (fragment.go:783-794)."""
        lo = block_id * HASH_BLOCK_SIZE * SLICE_WIDTH
        vals = self.storage.slice_range(lo, lo + HASH_BLOCK_SIZE * SLICE_WIDTH)
        return vals // SLICE_WIDTH, vals % SLICE_WIDTH

    @_loaded
    def merge_block(self, block_id: int, data: List[Tuple[np.ndarray, np.ndarray]]):
        """Majority-consensus merge of one block across replicas
        (fragment.go:796-920). `data` holds each remote's (rowIDs, colIDs).
        Applies the consensus locally; returns per-remote (sets, clears)
        diffs as (rowIDs, colIDs) pair arrays."""
        lo = block_id * HASH_BLOCK_SIZE * SLICE_WIDTH
        hi = lo + HASH_BLOCK_SIZE * SLICE_WIDTH

        participants = [self.storage.slice_range(lo, hi)]
        for rows, cols in data:
            rows = np.asarray(rows, dtype=np.uint64)
            cols = np.asarray(cols, dtype=np.uint64)
            if rows.shape != cols.shape:
                raise ValueError("pair set mismatch")
            pos = rows * np.uint64(SLICE_WIDTH) + cols
            pos = pos[(pos >= lo) & (pos < hi)]
            participants.append(np.unique(pos))

        majority = (len(participants) + 1) // 2
        all_pos, counts = np.unique(np.concatenate(participants), return_counts=True)
        consensus = all_pos[counts >= majority]

        out = []
        for i, mine in enumerate(participants):
            sets = np.setdiff1d(consensus, mine, assume_unique=True)
            clears = np.setdiff1d(mine, consensus, assume_unique=True)
            if i == 0:
                self._apply_consensus(sets, clears)
            else:
                out.append((
                    (sets // SLICE_WIDTH, sets % SLICE_WIDTH),
                    (clears // SLICE_WIDTH, clears % SLICE_WIDTH),
                ))
        return out

    # Below this many diff bits the per-bit path wins: it preserves the
    # WAL and the incremental device log, and the bulk path's forced
    # snapshot costs more than a handful of appends.
    _CONSENSUS_BULK_MIN = 128

    def _apply_consensus(self, sets: np.ndarray, clears: np.ndarray):
        """Apply a consensus diff (storage positions) locally. Small
        diffs go bit-by-bit through set_bit/clear_bit (WAL-durable,
        device-log incremental). Large diffs — anti-entropy after real
        divergence, e.g. a replica restored from an old snapshot —
        apply as WAL-detached bulk storage ops plus one forced
        snapshot, mirroring import_bits: per bit, set_bit pays a WAL
        append, a row rematerialization, and a cache update, which on a
        100k-bit diff is minutes of Python loop against milliseconds of
        add_many/remove_many."""
        base = self.slice * SLICE_WIDTH
        if len(sets) + len(clears) < self._CONSENSUS_BULK_MIN:
            for p in sets:
                self.set_bit(int(p) // SLICE_WIDTH, base + int(p) % SLICE_WIDTH)
            for p in clears:
                self.clear_bit(int(p) // SLICE_WIDTH, base + int(p) % SLICE_WIDTH)
            return
        sets = np.asarray(sets, dtype=np.uint64)
        clears = np.asarray(clears, dtype=np.uint64)
        self.storage.op_writer = None
        try:
            if sets.size:
                self.storage.add_many(sets)
            if clears.size:
                self.storage.remove_many(clears)
        finally:
            self.storage.op_writer = self._wal
        self._mark_dirty(None)
        for r in np.unique(np.concatenate([sets, clears])
                           // np.uint64(SLICE_WIDTH)):
            self.cache.bulk_add(int(r), self.row(int(r)).count())
        self.cache.invalidate()
        # Runs under the caller's _mu (merge_block): WAITING for the
        # snapshot here would deadlock with its finish step, which
        # needs this lock. Request coverage and return — anti-entropy
        # re-converges if a crash beats the background write.
        self._request_snapshot_locked()

    # -- cache persistence ---------------------------------------------------

    @_locked
    def flush_cache(self):
        """Persist cache pairs as JSON (analog of the protobuf `.cache`
        file, fragment.go:1073-1093)."""
        if self._pending_load:
            return  # never touched: cache on disk is still current
        try:
            pairs = self.cache.top() or [(i, self.cache.get(i)) for i in self.cache.ids()]
            tmp = self.cache_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump([[int(i), int(n)] for i, n in pairs], f)
            os.replace(tmp, self.cache_path)
        except OSError:
            pass

    def _load_cache(self):
        if not os.path.exists(self.cache_path):
            # No persisted cache (fresh fragment or crash before flush):
            # rebuild from storage so TopN stays correct. Row IDs come
            # straight from the container keys (key >> 4 = rowID), so this
            # costs one count per distinct row, not a full scan.
            self.rebuild_cache()
            return
        try:
            with open(self.cache_path) as f:
                pairs = json.load(f)
        except (OSError, ValueError):
            # Corrupt/truncated cache file (e.g. crash mid-flush): rebuild
            # from storage rather than serving an empty TopN cache.
            self.rebuild_cache()
            return
        for id_, _n in pairs:
            self.cache.bulk_add(int(id_), self.row(int(id_)).count())
        self.cache.recalculate()

    @_loaded
    def rebuild_cache(self):
        """Recompute all row counts from storage (crash recovery path)."""
        row_span = SLICE_WIDTH >> 16  # containers per row; keep jax out of host paths

        row_ids = sorted({k // row_span for k in self.storage.keys})
        for r in row_ids:
            self.cache.bulk_add(r, self.row(r).count())
        if row_ids:
            self.cache.recalculate()

    # -- backup/restore ------------------------------------------------------

    @_loaded
    def write_to_tar(self, fileobj):
        """Stream data+cache as a tar archive (fragment.go:1095-1153)."""
        with tarfile.open(fileobj=fileobj, mode="w|") as tar:
            # footer=True: transfers (rebalance, read-repair) carry the
            # integrity footer, so the receiver verifies the wire bytes
            # with the same machinery that guards the disk.
            data = self.storage.to_bytes(footer=True)
            info = tarfile.TarInfo("data")
            info.size = len(data)
            info.mtime = int(time.time())
            tar.addfile(info, io.BytesIO(data))
            cache = json.dumps(
                [[int(i), int(n)] for i, n in (self.cache.top() or [])]
            ).encode()
            info = tarfile.TarInfo("cache")
            info.size = len(cache)
            info.mtime = int(time.time())
            tar.addfile(info, io.BytesIO(cache))

    def read_from_tar(self, fileobj):
        """Restore from a tar archive produced by write_to_tar
        (fragment.go:1155-1266). The data member replaces storage
        wholesale, then rides the non-blocking snapshot engine —
        applied only between snapshots (a freeze taken before the
        swap would persist the pre-restore image) and waited on
        OUTSIDE _mu."""
        with tarfile.open(fileobj=fileobj, mode="r|") as tar:
            for member in tar:
                buf = tar.extractfile(member).read()
                if member.name == "data":
                    while True:
                        with self._mu:
                            self.ensure_loaded()
                            if not self._snapshotting:
                                self.storage.op_writer = None
                                self.storage = Bitmap.from_bytes(buf)
                                self.op_n = self.storage.op_n
                                self.storage.op_writer = self._wal
                                self._mark_dirty(None)
                                target = self._snap_gen + 1
                                self._start_snapshot()
                                break
                            done = self._snap_done
                        done.wait()
                    self._await_snapshot(target)
                elif member.name == "cache":
                    with self._mu:
                        self.ensure_loaded()
                        for id_, _n in json.loads(buf or b"[]"):
                            self.cache.bulk_add(
                                int(id_), self.row(int(id_)).count())
                        self.cache.recalculate()

    # -- device compute image ------------------------------------------------

    @property
    @_loaded
    def pool(self):
        """(FragmentPool, row_ids) device image.

        Maintained INCREMENTALLY: writes that stay inside existing
        containers are folded from the mutation log into one device
        scatter (ops.pool.apply_pool_mutations) — the pool re-upload
        the reference avoids via mmap (fragment.go:371-413) is avoided
        here by never leaving the device. Only container churn (new
        container / emptied container / bulk import) forces a rebuild.
        """
        if not self._pool_dirty and self._pool is not None:
            return self._pool, self._pool_row_ids
        if self._pool is not None and self._try_pool_update():
            self._pool_dirty = False
            return self._pool, self._pool_row_ids

        import jax

        from ..ops import FragmentPool, build_pool_arrays

        keys, words, n, row_ids = build_pool_arrays(self.storage)
        self._pool = FragmentPool(
            keys=jax.device_put(keys), words=jax.device_put(words),
            n=jax.device_put(n))
        self._pool_keys_host = keys
        self._pool_row_ids = row_ids
        self._pool_gen = self.generation
        self._pool_dirty = False
        return self._pool, self._pool_row_ids

    def _try_pool_update(self) -> bool:
        """Apply logged writes to the existing device pool via scatter.
        False when the log was pruned, churned, or targets rows outside
        the staged dense table — the caller rebuilds."""
        entries = self.log_since(self._pool_gen)
        if entries is None or any(e[2] for e in entries):
            return False
        if not entries:
            return True
        from ..ops.pool import (
            apply_pool_mutations,
            fold_log_entries,
            pad_mutation_plan,
            plan_slice_mutations,
        )

        pos, val = fold_log_entries(entries)
        try:
            plan = plan_slice_mutations(
                self._pool_keys_host, self._pool_row_ids, pos, val)
        except KeyError:
            return False
        batch = pad_mutation_plan(plan, self._pool.capacity)
        self._pool = apply_pool_mutations(self._pool, *batch)
        self._pool_gen = self.generation
        return True
