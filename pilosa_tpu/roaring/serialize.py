"""On-disk format + op log, byte-compatible with the reference.

Layout (reference /root/reference/roaring/roaring.go:475-614):

    u32 cookie (12346) | u32 containerCount
    containerCount x { u64 key | u32 n-1 }            # 12-byte headers
    containerCount x { u32 absolute offset }
    container blocks: array -> n x u32 LE; bitmap -> 1024 x u64 LE
    op log: repeated { u8 type | u64 value | u32 fnv32a(first 9 bytes) }

All little-endian. Containers with n <= 4096 are stored in array form,
larger in bitmap form (the reader infers form from n).
"""

from __future__ import annotations

import struct

import numpy as np

from .bitmap import ARRAY_MAX_SIZE, BITMAP_N, Bitmap, Container

COOKIE = 12346
HEADER_SIZE = 8
OP_SIZE = 13


def fnv32a(data: bytes) -> int:
    """32-bit FNV-1a (reference op checksums, roaring.go:1595-1616)."""
    h = 2166136261
    for b in data:
        h ^= b
        h = (h * 16777619) & 0xFFFFFFFF
    return h


def write_op(w, typ: int, value: int) -> int:
    """Append one WAL op: {type u8, value u64, fnv32a u32} = 13 bytes."""
    body = struct.pack("<BQ", typ, value)
    w.write(body + struct.pack("<I", fnv32a(body)))
    return OP_SIZE


def read_ops(data: bytes):
    """Parse a run of WAL ops; yields (type, value). Raises on bad checksum."""
    off = 0
    while off < len(data):
        if off + OP_SIZE > len(data):
            raise ValueError(f"op data out of bounds: len={len(data) - off}")
        body = data[off : off + 9]
        (chk,) = struct.unpack_from("<I", data, off + 9)
        if chk != fnv32a(body):
            raise ValueError(
                f"checksum mismatch: exp={fnv32a(body):08x}, got={chk:08x}"
            )
        typ, value = struct.unpack("<BQ", body)
        yield typ, value
        off += OP_SIZE


def scan_ops(data: bytes):
    """Crash-tolerant WAL parse: returns (ops, valid_bytes, torn_bytes).

    A crash mid-`write_op` can leave exactly one damaged op at the END
    of the log — either a partial record (< 13 bytes) or a final full
    record whose checksum doesn't cover what actually hit the disk.
    That torn TAIL is recoverable: every op before it was acked off a
    completed write, so the loader truncates the tail and keeps the
    prefix. A bad checksum with MORE ops after it is a different animal
    — bit rot or a buggy writer mid-log — and still raises, because
    silently dropping acknowledged interior ops would corrupt state.
    """
    ops = []
    off = 0
    n = len(data)
    while off < n:
        if off + OP_SIZE > n:
            return ops, off, n - off  # partial trailing record: torn
        body = data[off : off + 9]
        (chk,) = struct.unpack_from("<I", data, off + 9)
        if chk != fnv32a(body):
            if off + OP_SIZE == n:
                return ops, off, OP_SIZE  # torn final record
            raise ValueError(
                f"checksum mismatch mid-log at offset {off}: "
                f"exp={fnv32a(body):08x}, got={chk:08x}")
        ops.append(struct.unpack("<BQ", body))
        off += OP_SIZE
    return ops, off, 0


def _container_bytes(c: Container) -> bytes:
    if c.is_array():
        return c.array.astype("<u4").tobytes()
    return c.bitmap.astype("<u8").tobytes()


def write_bitmap(b: Bitmap, w) -> int:
    """Serialize the snapshot region (no ops). Returns bytes written."""
    entries = [
        (key, c) for key, c in zip(b.keys, b.containers) if c.n > 0
    ]
    n_written = 0
    header = struct.pack("<II", COOKIE, len(entries))
    keyhdrs = b"".join(
        struct.pack("<QI", key, c.n - 1) for key, c in entries
    )
    blocks = [_container_bytes(c) for _, c in entries]
    offset = HEADER_SIZE + len(entries) * 12 + len(entries) * 4
    offsets = bytearray()
    for blk in blocks:
        offsets += struct.pack("<I", offset)
        offset += len(blk)
    for chunk in (header, keyhdrs, bytes(offsets), *blocks):
        w.write(chunk)
        n_written += len(chunk)
    return n_written


def read_bitmap(data: bytes, truncate_torn_tail: bool = False) -> Bitmap:
    """Parse snapshot + replay trailing op log (reference roaring.go:536-614).

    With `truncate_torn_tail=True`, a damaged FINAL op (partial record
    or bad checksum on the last complete record — the signature of a
    crash mid-append) is dropped instead of raising; the returned
    bitmap carries `torn_tail_bytes` so the caller can truncate the
    backing file before reopening it for append. Mid-log corruption
    still raises either way.
    """
    if len(data) < HEADER_SIZE:
        raise ValueError("data too small")
    cookie, key_n = struct.unpack_from("<II", data, 0)
    if cookie != COOKIE:
        raise ValueError("invalid roaring file")

    # Validate the whole header region up front: a truncated or
    # corrupt file must surface as ValueError, not struct.error /
    # numpy buffer errors (reference UnmarshalBinary bounds behavior).
    ops_offset = HEADER_SIZE + key_n * 12
    if ops_offset + key_n * 4 > len(data):
        raise ValueError(
            f"truncated roaring file: {key_n} containers declared, "
            f"{len(data)} bytes")

    b = Bitmap()
    ns = []
    for i in range(key_n):
        key, n_minus_1 = struct.unpack_from("<QI", data, HEADER_SIZE + i * 12)
        b.keys.append(key)
        ns.append(n_minus_1 + 1)

    end = ops_offset + key_n * 4
    for i in range(key_n):
        (offset,) = struct.unpack_from("<I", data, ops_offset + i * 4)
        n = ns[i]
        size = n * 4 if n <= ARRAY_MAX_SIZE else BITMAP_N * 8
        if offset + size > len(data):
            raise ValueError(
                f"offset out of bounds: off={offset}+{size}, "
                f"len={len(data)}")
        if n <= ARRAY_MAX_SIZE:
            arr = np.frombuffer(data, dtype="<u4", count=n, offset=offset)
            b.containers.append(Container(array=arr.astype(np.uint32)))
        else:
            words = np.frombuffer(data, dtype="<u8", count=BITMAP_N, offset=offset)
            b.containers.append(Container(bitmap=words.astype(np.uint64)))
        end = offset + size

    if truncate_torn_tail:
        ops, _, torn = scan_ops(data[end:])
        b.torn_tail_bytes = torn
    else:
        ops = read_ops(data[end:])
        b.torn_tail_bytes = 0
    for typ, value in ops:
        if typ == 0:
            b._add_one(value)
        elif typ == 1:
            b._remove_one(value)
        else:
            raise ValueError(f"invalid op type: {typ}")
        b.op_n += 1
    return b
