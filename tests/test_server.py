"""Server runtime + multi-node tests over real HTTP on loopback
(the model: /root/reference/client_test.go createCluster — N real
engines in one process sharing a cluster view — and
server/server_test.go full-node integration)."""

import socket
import time

import pytest

from pilosa_tpu import SLICE_WIDTH
from pilosa_tpu.api import InternalClient
from pilosa_tpu.config import Config, parse_duration
from pilosa_tpu.core.syncer import FragmentSyncer, HolderSyncer
from pilosa_tpu.server import Server
from pilosa_tpu.wire import pb


def free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


@pytest.fixture
def cluster2(tmp_path):
    """Two live Server nodes sharing one static cluster."""
    ports = free_ports(2)
    hosts = [f"127.0.0.1:{p}" for p in ports]
    servers = []
    for i, h in enumerate(hosts):
        c = Config()
        c.data_dir = str(tmp_path / f"node{i}")
        c.host = h
        c.cluster_hosts = hosts
        c.replica_n = 1
        # Daemons effectively off; tests trigger syncs explicitly.
        c.anti_entropy_interval = 3600
        c.polling_interval = 3600
        s = Server(c)
        s.open()
        servers.append(s)
    yield servers, hosts
    for s in servers:
        s.close()


class TestConfig:
    def test_parse_duration(self):
        assert parse_duration("10m") == 600
        assert parse_duration("1h30m") == 5400
        assert parse_duration("250ms") == 0.25
        assert parse_duration(5) == 5.0
        # Sub-millisecond Go units (?deadline= budgets go this small).
        assert abs(parse_duration("50us") - 50e-6) < 1e-12
        assert abs(parse_duration("50µs") - 50e-6) < 1e-12
        assert abs(parse_duration("100ns") - 100e-9) < 1e-15
        with pytest.raises(ValueError):
            parse_duration("5x")

    def test_toml_roundtrip(self):
        c = Config.from_toml(
            'host = "h:1"\n[cluster]\nreplicas = 2\n'
            'hosts = ["h:1", "h:2"]\n[anti-entropy]\ninterval = "5m"\n',
            is_text=True)
        assert c.replica_n == 2
        assert c.cluster_hosts == ["h:1", "h:2"]
        assert c.anti_entropy_interval == 300
        # default printer parses back
        c2 = Config.from_toml(Config().to_toml(), is_text=True)
        assert c2.host == Config().host

    def test_reference_plugins_section_loads_unchanged(self):
        """A reference TOML carrying the vestigial [plugins] path
        (config.go:50 — no loader exists there either) parses without
        error; the field is accepted and inert."""
        c = Config.from_toml(
            'data-dir = "/tmp/p"\n[plugins]\npath = "/opt/plugins"\n'
            '[cluster]\nreplicas = 3\n', is_text=True)
        assert c.plugins_path == "/opt/plugins"
        assert c.replica_n == 3
        assert Config().plugins_path == ""


class TestMultiNode:
    def test_schema_broadcast(self, cluster2):
        servers, hosts = cluster2
        InternalClient(hosts[0]).create_index("i", columnLabel="cid")
        InternalClient(hosts[0]).create_frame("i", "f")
        # node 1 learned the schema synchronously via broadcast
        idx = servers[1].holder.index("i")
        assert idx is not None and idx.column_label == "cid"
        assert idx.frame("f") is not None

    def test_distributed_query_both_coordinators(self, cluster2):
        servers, hosts = cluster2
        cli0 = InternalClient(hosts[0])
        cli0.create_index("i")
        cli0.create_frame("i", "f")
        # bits across 8 slices -> both nodes own some
        n = 8
        q = "".join(
            f"SetBit(rowID=1, frame=f, columnID={s * SLICE_WIDTH + s})"
            for s in range(n))
        assert cli0.execute_query(None, "i", q, [], remote=False) == [True] * n
        for h in hosts:
            res = InternalClient(h).execute_query(
                None, "i", "Count(Bitmap(rowID=1, frame=f))", [],
                remote=False)
            assert res == [n]
        # each node holds only its own slices locally
        local_bits = [
            sum(s.holder.fragment("i", "f", "standard", sl).count()
                for sl in range(n)
                if s.holder.fragment("i", "f", "standard", sl) is not None)
            for s in servers]
        assert sum(local_bits) == n
        assert all(b < n for b in local_bits)

    def test_distributed_topn(self, cluster2):
        servers, hosts = cluster2
        cli = InternalClient(hosts[0])
        cli.create_index("i")
        cli.create_frame("i", "f")
        q = []
        for s in range(4):
            q.append(f"SetBit(rowID=10, frame=f, columnID={s * SLICE_WIDTH})")
        q.append(f"SetBit(rowID=20, frame=f, columnID=0)")
        cli.execute_query(None, "i", "".join(q), [], remote=False)
        res = InternalClient(hosts[1]).execute_query(
            None, "i", "TopN(frame=f, n=2)", [], remote=False)
        assert res == [[(10, 4), (20, 1)]]

    def test_distributed_query_device_serving(self, cluster2):
        """Both nodes serve their owned slice subset through the mesh
        engine (slice-ownership masks): a cluster-wide Count is the sum
        of two masked collectives + HTTP merge, and the device answer
        matches the host executors'."""
        servers, hosts = cluster2
        cli0 = InternalClient(hosts[0])
        cli0.create_index("i")
        cli0.create_frame("i", "f")
        n = 8
        q = "".join(
            f"SetBit(rowID=1, frame=f, columnID={s * SLICE_WIDTH + s})"
            f"SetBit(rowID=2, frame=f, columnID={s * SLICE_WIDTH + s})"
            for s in range(n))
        cli0.execute_query(None, "i", q, [], remote=False)
        for s in servers:
            s.executor.use_device = True
        pql = "Count(Intersect(Bitmap(rowID=1, frame=f), Bitmap(rowID=2, frame=f)))"
        for h in hosts:
            assert InternalClient(h).execute_query(
                None, "i", pql, [], remote=False) == [n]
        # Every node's manager served at least one masked batch (no
        # node answered for slices it doesn't own).
        for s in servers:
            mgr = s.executor.mesh_manager()
            assert mgr is not None and mgr.stats["count"] >= 1, mgr and mgr.stats
            sv = mgr._views[("i", "f", "standard")]
            owned = [sl for sl in range(n)
                     if sv.slice_gens[sl] is not None]
            assert 0 < len(owned) < n  # a strict subset is staged local

    def test_status_poll_merges_remote_schema(self, cluster2):
        servers, hosts = cluster2
        # Create schema only on node 1's holder (no broadcast).
        idx = servers[1].holder.create_index_if_not_exists("remote_only")
        idx.create_frame_if_not_exists("f")
        servers[0]._status_poll_tick()
        assert servers[0].holder.index("remote_only") is not None
        assert servers[0].holder.frame("remote_only", "f") is not None

    def test_status_poll_marks_dead_node_down(self, cluster2):
        servers, hosts = cluster2
        servers[1].close()
        servers[0]._status_poll_tick()
        states = servers[0].cluster.node_states()
        assert states[hosts[1]] == "DOWN"
        assert states[hosts[0]] == "UP"

    def test_cluster_status_endpoint(self, cluster2):
        servers, hosts = cluster2
        servers[0]._status_poll_tick()
        import urllib.request
        with urllib.request.urlopen(f"http://{hosts[0]}/status") as r:
            import json
            nodes = json.loads(r.read())["nodes"]
        assert {n["host"] for n in nodes} == set(hosts)

    def test_create_slice_message(self, cluster2):
        servers, hosts = cluster2
        cli = InternalClient(hosts[0])
        cli.create_index("i")
        cli.create_frame("i", "f")
        # a bit in slice 5 owned by node0 -> async CreateSliceMessage
        # tells node1 the index now spans 6 slices
        target = None
        for s in range(1, 32):
            owners = servers[0].cluster.fragment_nodes("i", s)
            if owners[0].host == hosts[0]:
                target = s
                break
        cli.execute_query(
            None, "i",
            f"SetBit(rowID=1, frame=f, columnID={target * SLICE_WIDTH})", [],
            remote=False)
        deadline = time.time() + 5
        while time.time() < deadline:
            if servers[1].holder.index("i").max_slice() == target:
                break
            time.sleep(0.05)
        assert servers[1].holder.index("i").max_slice() == target


class TestAntiEntropy:
    def test_fragment_sync_repairs_divergence(self, cluster2):
        servers, hosts = cluster2
        cli = InternalClient(hosts[0])
        cli.create_index("i")
        cli.create_frame("i", "f")
        # Manufacture divergence in slice 0 between replicas: write
        # directly to each holder, bypassing routing.
        s0, s1 = servers
        f0 = s0.holder.frame("i", "f")
        f1 = s1.holder.frame("i", "f")
        f0.set_bit(1, 3)
        f1.set_bit(1, 3)       # both agree on (1,3)
        f0.set_bit(1, 5)       # only node0 has (1,5)
        # Majority-merge with 2 participants: ties keep consensus at
        # ceil(2/2)=1 vote -> union. Sync node0's copy of slice 0.
        syncer = HolderSyncer(s0.holder, s0.host, s0.cluster,
                              s0.client.for_host)
        syncer.sync_fragment("i", "f", "standard", 0)
        # node1 received the SetBit diff push
        res = InternalClient(hosts[1]).execute_query(
            None, "i", "Bitmap(rowID=1, frame=f)", [0], remote=True)
        assert sorted(res[0].columns()) == [3, 5]

    def test_attr_sync(self, cluster2):
        servers, hosts = cluster2
        cli = InternalClient(hosts[0])
        cli.create_index("i")
        cli.create_frame("i", "f")
        s0, s1 = servers
        # node1 has attrs node0 lacks
        s1.holder.index("i").column_attr_store.set_attrs(7, {"name": "x"})
        syncer = HolderSyncer(s0.holder, s0.host, s0.cluster,
                              s0.client.for_host)
        syncer.sync_index(s0.holder.index("i"))
        assert s0.holder.index("i").column_attr_store.attrs(7) == {
            "name": "x"}

    def test_holder_sync_full_walk(self, cluster2):
        servers, hosts = cluster2
        cli = InternalClient(hosts[0])
        cli.create_index("i")
        cli.create_frame("i", "f")
        s0, s1 = servers
        s1.holder.frame("i", "f").set_bit(2, 9)
        syncer = HolderSyncer(s0.holder, s0.host, s0.cluster,
                              s0.client.for_host)
        syncer.sync_holder()
        # whichever node owns slice 0, both converge on the bit
        for s in servers:
            frag = s.holder.fragment("i", "f", "standard", 0)
            if frag is not None and s.cluster.owns_fragment(
                    s.host, "i", 0):
                assert sorted(frag.row(2).columns()) == [9]


class TestFrameRestore:
    def test_restore_pulls_remote_fragments(self, cluster2):
        servers, hosts = cluster2
        cli0, cli1 = InternalClient(hosts[0]), InternalClient(hosts[1])
        cli0.create_index("i")
        cli0.create_frame("i", "f")
        # seed data only into node0's local holder
        servers[0].holder.frame("i", "f").set_bit(4, 8)
        # node1 restores frame f from node0
        status, _ = cli1._do(
            "POST", "/index/i/frame/f/restore", params={"host": hosts[0]})
        assert status == 200
        frag = servers[1].holder.fragment("i", "f", "standard", 0)
        assert frag is not None
        assert sorted(frag.row(4).columns()) == [8]


class TestReceiveMessage:
    def test_receive_create_and_delete(self, tmp_path):
        c = Config()
        c.data_dir = str(tmp_path / "n")
        s = Server(c)
        s.holder.open()
        s.receive_message(pb.CreateIndexMessage(
            index="i", meta=pb.IndexMeta(column_label="cid")))
        assert s.holder.index("i").column_label == "cid"
        s.receive_message(pb.CreateFrameMessage(
            index="i", frame="f", meta=pb.FrameMeta(row_label="rid")))
        assert s.holder.frame("i", "f").row_label == "rid"
        s.receive_message(pb.CreateSliceMessage(index="i", slice=4))
        assert s.holder.index("i").max_slice() == 4
        s.receive_message(pb.DeleteFrameMessage(index="i", frame="f"))
        assert s.holder.frame("i", "f") is None
        s.receive_message(pb.DeleteIndexMessage(index="i"))
        assert s.holder.index("i") is None
        s.holder.close()


class TestRegressionsFromReview:
    def test_empty_remote_row_result_merges(self, cluster2):
        """An empty Row from a remote node must decode as a Row, not
        Count(0) (wire kind tag)."""
        servers, hosts = cluster2
        cli = InternalClient(hosts[0])
        cli.create_index("i")
        cli.create_frame("i", "f")
        # row 1 exists only in a node0-owned slice; another row forces a
        # second slice owned by node1 so the fan-out hits both nodes.
        s_own = {h: None for h in hosts}
        for s in range(32):
            owner = servers[0].cluster.fragment_nodes("i", s)[0].host
            if s_own[owner] is None:
                s_own[owner] = s
        q = (f"SetBit(rowID=1, frame=f, columnID="
             f"{s_own[hosts[0]] * SLICE_WIDTH})"
             f"SetBit(rowID=2, frame=f, columnID="
             f"{s_own[hosts[1]] * SLICE_WIDTH})")
        cli.execute_query(None, "i", q, [], remote=False)
        for h in hosts:
            res = InternalClient(h).execute_query(
                None, "i", "Bitmap(rowID=1, frame=f)", [], remote=False)
            assert sorted(res[0].columns()) == [s_own[hosts[0]] * SLICE_WIDTH]
            res = InternalClient(h).execute_query(
                None, "i", "TopN(frame=f, n=10)", [], remote=False)
            assert sorted(res[0]) == [(1, 1), (2, 1)]

    def test_sync_tolerates_missing_remote_fragment(self, cluster2):
        """FragmentSyncer treats a replica without the fragment as empty
        (reference fragment.go:1345) instead of aborting."""
        servers, hosts = cluster2
        cli = InternalClient(hosts[0])
        cli.create_index("i")
        cli.create_frame("i", "f")
        s0, s1 = servers
        # only node0 has the fragment
        s0.holder.frame("i", "f").set_bit(1, 3)
        assert s1.holder.fragment("i", "f", "standard", 0) is None
        syncer = HolderSyncer(s0.holder, s0.host, s0.cluster,
                              s0.client.for_host)
        syncer.sync_fragment("i", "f", "standard", 0)
        # the consensus bit was pushed to node1
        res = InternalClient(hosts[1]).execute_query(
            None, "i", "Bitmap(rowID=1, frame=f)", [0], remote=True)
        assert sorted(res[0].columns()) == [3]


class TestGossipCluster:
    """Two live Server nodes clustered via the gossip transport
    (reference server/server.go:159-176 gossip wiring)."""

    def _wait(self, fn, timeout=10.0):
        from tests.test_gossip import wait_until
        return wait_until(fn, timeout=timeout)

    def test_gossip_schema_broadcast(self, tmp_path):
        ports = free_ports(2)
        hosts = [f"127.0.0.1:{p}" for p in ports]
        gports = free_ports(2)
        servers = []
        for i, h in enumerate(hosts):
            c = Config()
            c.data_dir = str(tmp_path / f"gnode{i}")
            c.host = h
            c.cluster_hosts = hosts
            c.cluster_type = "gossip"
            c.gossip_port = gports[i]
            if i > 0:
                c.gossip_seed = f"127.0.0.1:{gports[0]}"
            c.anti_entropy_interval = 3600
            c.polling_interval = 3600
            s = Server(c)
            s.open()
            servers.append(s)
        try:
            a, b = servers
            # Membership converges through SWIM probes.
            assert self._wait(lambda: set(a.node_set.nodes()) == set(hosts))
            assert self._wait(lambda: set(b.node_set.nodes()) == set(hosts))
            # Schema changes ride the gossip broadcast plane.
            InternalClient(hosts[0]).create_index("gi")
            InternalClient(hosts[0]).create_frame("gi", "gf")
            assert self._wait(lambda: b.holder.frame("gi", "gf") is not None)
            # Liveness feeds cluster node states (UP for both).
            states = a.cluster.node_states()
            assert all(v == "UP" for v in states.values()), states
        finally:
            for s in servers:
                s.close()

    def test_unknown_cluster_type_rejected(self, tmp_path):
        c = Config()
        c.data_dir = str(tmp_path / "bad")
        c.cluster_type = "gosip"
        with pytest.raises(ValueError, match="unknown cluster type"):
            Server(c)


class TestStatsD:
    """Dogstatsd backend (reference datadog/datadog.go analog)."""

    def _recv_lines(self, sock, timeout=3.0):
        sock.settimeout(timeout)
        data, _ = sock.recvfrom(65536)
        return data.decode().split("\n")

    def test_wire_format_and_tags(self):
        from pilosa_tpu.utils import StatsDStats
        agent = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        agent.bind(("127.0.0.1", 0))
        st = StatsDStats(addr=agent.getsockname(), flush_interval=9999)
        tagged = st.with_tags("index:i", "frame:f")
        st.count("setBit", 2)
        tagged.gauge("maxSlice", 7)
        tagged.timing("query", 1500)
        st.flush()
        lines = self._recv_lines(agent)
        assert "pilosa.setBit:2|c" in lines
        assert "pilosa.maxSlice:7|g|#index:i,frame:f" in lines
        assert "pilosa.query:1.5|ms|#index:i,frame:f" in lines
        st.close()
        agent.close()

    def test_overflow_flushes(self):
        from pilosa_tpu.utils import StatsDStats
        agent = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        agent.bind(("127.0.0.1", 0))
        st = StatsDStats(addr=agent.getsockname(), max_payload=64,
                         flush_interval=9999)
        for i in range(20):
            st.count(f"metric{i}")
        lines = self._recv_lines(agent)
        assert all(len("\n".join(lines)) <= 64 for _ in [0])
        assert lines[0] == "pilosa.metric0:1|c"
        st.close()
        agent.close()

    def test_dead_agent_never_raises(self):
        from pilosa_tpu.utils import StatsDStats
        st = StatsDStats(addr=("127.0.0.1", 1))  # nothing listens
        for i in range(100):
            st.count("x")
        st.flush()
        st.close()


class TestQuickProperty:
    """Randomized SetBit consistency through the full node — the analog
    of the reference's testing/quick property test
    (server/server_test.go TestMain_Set_Quick)."""

    def test_random_setbits_consistent(self, tmp_path):
        import random

        rng = random.Random(0xC0FFEE)
        port = free_ports(1)[0]
        host = f"127.0.0.1:{port}"
        c = Config()
        c.data_dir = str(tmp_path / "quick")
        c.host = host
        c.cluster_hosts = [host]
        c.anti_entropy_interval = 3600
        c.polling_interval = 3600
        s = Server(c)
        s.open()
        try:
            cli = InternalClient(host)
            cli.create_index("q")
            cli.create_frame("q", "f")
            # Random writes across rows, slices, duplicates included.
            want = {}
            for _ in range(300):
                row = rng.randrange(4)
                col = rng.randrange(3 * SLICE_WIDTH)
                want.setdefault(row, set()).add(col)
                q = f"SetBit(rowID={row}, frame=f, columnID={col})"
                cli.execute_query(None, "q", q, [], remote=False)
            # And some clears.
            for row in list(want):
                drop = set(rng.sample(sorted(want[row]),
                                      k=len(want[row]) // 5))
                want[row] -= drop
                for col in drop:
                    cli.execute_query(
                        None, "q",
                        f"ClearBit(rowID={row}, frame=f, columnID={col})",
                        [], remote=False)

            def check():
                for row, cols in want.items():
                    res = cli.execute_query(
                        None, "q", f"Bitmap(rowID={row}, frame=f)", [],
                        remote=False)
                    assert sorted(res[0].columns()) == sorted(cols), row
                res = cli.execute_query(None, "q", "TopN(frame=f, n=10)",
                                        [], remote=False)
                expect = sorted(((r, len(cs)) for r, cs in want.items()
                                 if cs), key=lambda p: (-p[1], p[0]))
                assert res[0] == expect

            check()
        finally:
            s.close()

        # Persistence: a fresh server over the same data dir agrees
        # (snapshot + WAL replay, fragment Reopen pattern).
        s2 = Server(c)
        s2.open(port=port)
        try:
            cli = InternalClient(host)
            for row, cols in want.items():
                res = cli.execute_query(
                    None, "q", f"Bitmap(rowID={row}, frame=f)", [],
                    remote=False)
                assert sorted(res[0].columns()) == sorted(cols), row
        finally:
            s2.close()


class TestReplicationFailover:
    """replica_n=2 over three real nodes: writes land on both owners,
    and queries survive a dead node via mapReduce re-split
    (executor.go:1140-1151) — over real HTTP, not mocks."""

    def test_query_survives_node_death(self, tmp_path):
        ports = free_ports(3)
        hosts = [f"127.0.0.1:{p}" for p in ports]
        servers = []
        for i, h in enumerate(hosts):
            c = Config()
            c.data_dir = str(tmp_path / f"rnode{i}")
            c.host = h
            c.cluster_hosts = hosts
            c.replica_n = 2
            c.anti_entropy_interval = 3600
            c.polling_interval = 3600
            s = Server(c)
            s.open()
            servers.append(s)
        try:
            cli = InternalClient(hosts[0])
            cli.create_index("r")
            cli.create_frame("r", "f")
            n_slices = 6
            pql = "".join(
                f"SetBit(rowID=1, frame=f, columnID={s * SLICE_WIDTH + s})"
                for s in range(n_slices))
            assert cli.execute_query(None, "r", pql, [], remote=False) \
                == [True] * n_slices

            # Each slice's fragment exists on BOTH replica owners.
            for sl in range(n_slices):
                owners = servers[0].cluster.fragment_nodes("r", sl)
                assert len(owners) == 2
                for node in owners:
                    srv = servers[hosts.index(node.host)]
                    frag = srv.holder.fragment("r", "f", "standard", sl)
                    assert frag is not None and frag.count() == 1, (sl, node)

            # Kill one node; mark it DOWN (status poll would normally do
            # this); queries from every surviving coordinator re-split
            # its slices onto the remaining replicas.
            dead = servers[2]
            dead.close()
            for s in servers[:2]:
                s.cluster.node_by_host(hosts[2]).set_state("DOWN")
            for h in hosts[:2]:
                res = InternalClient(h).execute_query(
                    None, "r", "Count(Bitmap(rowID=1, frame=f))", [],
                    remote=False)
                assert res == [n_slices], h
        finally:
            for s in servers:
                try:
                    s.close()
                except Exception:
                    pass
