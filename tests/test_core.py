"""Core data model tests, following the reference's wrapper-and-reopen
pattern (/root/reference/fragment_test.go, frame_test.go, holder_test.go)."""

import os
from datetime import datetime

import numpy as np
import pytest

from pilosa_tpu import SLICE_WIDTH
from pilosa_tpu.core import (
    AttrStore,
    Fragment,
    Frame,
    Holder,
    LRUCache,
    RankCache,
    Row,
    TimeQuantum,
    views_by_time,
    views_by_time_range,
)
from pilosa_tpu.core.attr import diff_blocks
from pilosa_tpu.errors import FrameExistsError
from pilosa_tpu.core.fragment import TopOptions


# -- fragment ---------------------------------------------------------------

@pytest.fixture
def frag(tmp_path):
    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
    f.open()
    yield f
    f.close()


def test_fragment_set_clear_row(frag):
    assert frag.set_bit(120, 1)
    assert frag.set_bit(120, 6)
    assert frag.set_bit(121, 0)
    assert not frag.set_bit(120, 1)  # already set
    assert list(frag.row(120)) == [1, 6]
    assert frag.count() == 3
    assert frag.clear_bit(120, 6)
    assert not frag.clear_bit(120, 6)
    assert list(frag.row(120)) == [1]


def test_fragment_row_absolute_columns(tmp_path):
    f = Fragment(str(tmp_path / "3"), "i", "f", "standard", 3)
    f.open()
    try:
        f.set_bit(5, 3 * SLICE_WIDTH + 100)
        assert list(f.row(5)) == [3 * SLICE_WIDTH + 100]
    finally:
        f.close()


def test_fragment_persistence_roundtrip(tmp_path):
    path = str(tmp_path / "0")
    f = Fragment(path, "i", "f", "standard", 0)
    f.open()
    f.set_bit(1, 100)
    f.set_bit(2, 200)
    f.close()
    # WAL ops are on disk; reopen replays them.
    f2 = Fragment(path, "i", "f", "standard", 0)
    f2.open()
    try:
        assert list(f2.row(1)) == [100]
        assert list(f2.row(2)) == [200]
    finally:
        f2.close()


def test_fragment_snapshot_trigger(tmp_path):
    path = str(tmp_path / "0")
    f = Fragment(path, "i", "f", "standard", 0)
    f.max_op_n = 10
    f.open()
    for i in range(12):
        f.set_bit(0, i)
    # Snapshots run in the background now; wait for the flip to land.
    assert f.wait_snapshot(timeout=10)
    assert f.op_n <= 10  # snapshot reset
    f.close()
    f2 = Fragment(path, "i", "f", "standard", 0)
    f2.open()
    try:
        assert f2.row(0).count() == 12
    finally:
        f2.close()


def test_fragment_flock_exclusive(tmp_path):
    path = str(tmp_path / "0")
    f = Fragment(path, "i", "f", "standard", 0)
    f.open()
    try:
        f2 = Fragment(path, "i", "f", "standard", 0)
        with pytest.raises(RuntimeError, match="locked"):
            f2.open()
    finally:
        f.close()


def test_fragment_import_and_top(frag):
    # rows with decreasing cardinality
    rows, cols = [], []
    for r, n in [(10, 50), (11, 40), (12, 30), (13, 5)]:
        rows += [r] * n
        cols += list(range(n))
    frag.import_bits(rows, cols)
    top = frag.top(TopOptions(n=2))
    assert top == [(10, 50), (11, 40)]
    # src-intersection recount (reference fragment.go Top w/ Src)
    src = Row(range(10))
    top = frag.top(TopOptions(n=3, src=src))
    assert top == [(10, 10), (11, 10), (12, 10)]
    # row_ids filter disables truncation
    top = frag.top(TopOptions(row_ids=[12, 13]))
    assert top == [(12, 30), (13, 5)]


def test_fragment_blocks_and_merge(tmp_path):
    f1 = Fragment(str(tmp_path / "a"), "i", "f", "standard", 0)
    f2 = Fragment(str(tmp_path / "b"), "i", "f", "standard", 0)
    f1.open(), f2.open()
    try:
        for r, c in [(1, 1), (1, 2), (2, 5)]:
            f1.set_bit(r, c)
        for r, c in [(1, 1), (2, 5), (3, 9)]:
            f2.set_bit(r, c)
        b1, b2 = dict(f1.blocks()), dict(f2.blocks())
        assert b1 != b2
        # Merge remote block 0 into f1: consensus of 2 participants
        # (majority = (2+1)//2 = 1... ties resolve to set).
        rows, cols = f2.block_data(0)
        diffs = f1.merge_block(0, [(rows, cols)])
        # consensus = union at majority 1: {1,1},{1,2},{2,5},{3,9}
        assert set(f1.for_each_bit()) == {(1, 1), (1, 2), (2, 5), (3, 9)}
        (sets, clears) = diffs[0]
        assert list(zip(*sets)) == [(1, 2)]  # remote needs (1,2)
        assert list(zip(*clears))[0:0] == []
    finally:
        f1.close(), f2.close()


def test_fragment_merge_large_divergence(tmp_path):
    """100k-bit consensus diffs apply through the bulk
    add_many/remove_many path (per-bit set_bit/clear_bit loops took
    minutes here) and still converge to exact majority state that
    survives a reopen."""
    f1 = Fragment(str(tmp_path / "a"), "i", "f", "standard", 0)
    f1.open()
    try:
        # Local state A: 100k bits across rows 0..9. Two remotes agree
        # on a DISJOINT state B — majority (2 of 3) clears all of A and
        # sets all of B.
        n = 100_000
        rows_a = np.arange(n, dtype=np.uint64) % 10
        cols_a = np.arange(n, dtype=np.uint64) * 2
        f1.import_bits(rows_a, cols_a)
        rows_b = np.arange(n, dtype=np.uint64) % 10
        cols_b = np.arange(n, dtype=np.uint64) * 2 + 1
        diffs = f1.merge_block(0, [(rows_b, cols_b), (rows_b, cols_b)])
        want = set(zip(rows_b.tolist(), cols_b.tolist()))
        assert set(f1.for_each_bit()) == want
        assert len(diffs) == 2  # remotes already hold the consensus
        for (sets, clears) in diffs:
            assert len(sets[0]) == 0
            assert len(clears[0]) == 0
        f1.close()
        f1 = Fragment(str(tmp_path / "a"), "i", "f", "standard", 0)
        f1.open()  # the bulk path snapshotted: state is durable
        assert f1.storage.count() == n
        assert set(f1.for_each_bit()) == want
    finally:
        f1.close()


def test_fragment_merge_small_diff_uses_wal(tmp_path):
    """Diffs below the bulk threshold keep the per-bit WAL path: no
    forced snapshot, ops appended."""
    f1 = Fragment(str(tmp_path / "a"), "i", "f", "standard", 0)
    f1.open()
    try:
        f1.set_bit(1, 1)
        op_n0 = f1.op_n
        f1.merge_block(0, [(np.asarray([1, 1]), np.asarray([1, 2])),
                           (np.asarray([1, 1]), np.asarray([1, 2]))])
        assert set(f1.for_each_bit()) == {(1, 1), (1, 2)}
        assert f1.op_n > op_n0  # WAL appended, not snapshot-reset
    finally:
        f1.close()


def test_fragment_row_cache_bounded_lru(frag, monkeypatch):
    """_row_cache holds at most _ROW_CACHE_MAX materialized rows and
    evicts least-recently-USED (a re-read refreshes recency)."""
    monkeypatch.setattr(Fragment, "_ROW_CACHE_MAX", 4)
    for r in range(6):
        frag.set_bit(r, r)
    for r in range(4):
        frag.row(r)
    assert set(frag._row_cache) == {0, 1, 2, 3}
    frag.row(0)  # refresh row 0's recency
    frag.row(4)  # evicts row 1 (LRU), not row 0
    assert set(frag._row_cache) == {0, 2, 3, 4}
    frag.row(5)
    assert set(frag._row_cache) == {0, 3, 4, 5}
    assert len(frag._row_cache) == 4
    assert frag.row(1).count() == 1  # evicted rows rematerialize fine


def test_fragment_checksum_changes_on_write(frag):
    c0 = frag.checksum()
    frag.set_bit(0, 0)
    assert frag.checksum() != c0


def test_fragment_tar_roundtrip(tmp_path):
    import io
    f1 = Fragment(str(tmp_path / "a"), "i", "f", "standard", 0)
    f1.open()
    f1.import_bits([1, 1, 2], [3, 4, 5])
    buf = io.BytesIO()
    f1.write_to_tar(buf)
    f1.close()
    buf.seek(0)
    f2 = Fragment(str(tmp_path / "b"), "i", "f", "standard", 0)
    f2.open()
    try:
        f2.read_from_tar(buf)
        assert set(f2.for_each_bit()) == {(1, 3), (1, 4), (2, 5)}
    finally:
        f2.close()


# -- row --------------------------------------------------------------------

def test_row_cross_slice_ops():
    a = Row([1, SLICE_WIDTH + 1, 2 * SLICE_WIDTH + 3])
    b = Row([1, SLICE_WIDTH + 2, 2 * SLICE_WIDTH + 3])
    assert list(a.intersect(b)) == [1, 2 * SLICE_WIDTH + 3]
    assert a.union(b).count() == 4
    assert list(a.difference(b)) == [SLICE_WIDTH + 1]
    assert a.intersection_count(b) == 2
    assert a.count() == 3


# -- caches -----------------------------------------------------------------

def test_rank_cache_threshold_and_trim():
    clock = [0.0]
    c = RankCache(max_entries=3, clock=lambda: clock[0])
    for i, n in enumerate([100, 90, 80, 70, 60]):
        c.add(i, n)
        clock[0] += 11  # defeat the damper
    assert [p[0] for p in c.top()] == [0, 1, 2]
    # threshold gate: counts below threshold are ignored
    c.add(99, 1)
    assert c.get(99) == 0


def test_rank_cache_damper():
    clock = [0.0]
    c = RankCache(max_entries=10, clock=lambda: clock[0])
    c.add(1, 5)
    c.add(2, 50)  # within 10s: invalidate() doesn't resort...
    assert [p[0] for p in c.rankings] == [1]
    # ...but the read path recalculates when dirty (stale-TopN fix).
    assert [p[0] for p in c.top()] == [2, 1]
    # Damper window passed: invalidate() recalculates again.
    c.bulk_add(3, 100)
    clock[0] += 11
    c.invalidate()
    assert [p[0] for p in c.rankings] == [3, 2, 1]


def test_lru_cache_eviction():
    c = LRUCache(max_entries=2)
    c.add(1, 10)
    c.add(2, 20)
    c.get(1)
    c.add(3, 30)  # evicts 2 (least recently used)
    assert c.ids() == [1, 3]


# -- attrs ------------------------------------------------------------------

def test_attr_store(tmp_path):
    s = AttrStore(str(tmp_path / "attrs.db"))
    s.open()
    try:
        s.set_attrs(1, {"name": "a", "n": 5, "ok": True, "f": 1.5})
        s.set_attrs(1, {"n": 6, "name": None})
        assert s.attrs(1) == {"n": 6, "ok": True, "f": 1.5}
        with pytest.raises(TypeError):
            s.set_attrs(2, {"bad": [1, 2]})
        s.set_bulk_attrs({10: {"x": 1}, 250: {"y": 2}})
        blocks = s.blocks()
        assert [b for b, _ in blocks] == [0, 2]
        assert s.block_data(2) == {250: {"y": 2}}
    finally:
        s.close()


def test_attr_diff_blocks(tmp_path):
    a = AttrStore(str(tmp_path / "a.db"))
    b = AttrStore(str(tmp_path / "b.db"))
    a.open(), b.open()
    try:
        a.set_attrs(1, {"x": 1})
        b.set_attrs(1, {"x": 2})
        b.set_attrs(500, {"y": 1})
        assert diff_blocks(a.blocks(), b.blocks()) == [0, 5]
    finally:
        a.close(), b.close()


# -- time quantum ------------------------------------------------------------

def test_views_by_time():
    t = datetime(2017, 4, 9, 11)
    assert views_by_time("standard", t, TimeQuantum("YMDH")) == [
        "standard_2017", "standard_201704", "standard_20170409",
        "standard_2017040911",
    ]


def test_views_by_time_range_reference_vectors():
    # Expected values from /root/reference/time_test.go:88-126.
    cases = [
        ("Y", datetime(2000, 1, 1), datetime(2002, 1, 1),
         ["F_2000", "F_2001"]),
        ("YM", datetime(2000, 11, 1), datetime(2003, 3, 1),
         ["F_200011", "F_200012", "F_2001", "F_2002", "F_200301", "F_200302"]),
        ("YMD", datetime(2000, 11, 28), datetime(2003, 3, 2),
         ["F_20001128", "F_20001129", "F_20001130", "F_200012", "F_2001",
          "F_2002", "F_200301", "F_200302", "F_20030301"]),
        ("YMDH", datetime(2000, 11, 28, 22), datetime(2002, 3, 1, 3),
         ["F_2000112822", "F_2000112823", "F_20001129", "F_20001130",
          "F_200012", "F_2001", "F_200201", "F_200202", "F_2002030100",
          "F_2002030101", "F_2002030102"]),
        ("M", datetime(2000, 1, 1), datetime(2000, 3, 1),
         ["F_200001", "F_200002"]),
    ]
    for q, start, end, expected in cases:
        got = views_by_time_range("F", start, end, TimeQuantum(q))
        assert got == expected, q


# -- frame / index / holder ---------------------------------------------------

def test_frame_time_and_inverse_views(tmp_path):
    f = Frame(str(tmp_path / "f"), "i", "f", inverse_enabled=True,
              time_quantum="YM")
    f.open()
    try:
        f.set_bit(1, 9, t=datetime(2017, 4, 1))
        assert sorted(f.views) == [
            "inverse", "inverse_2017", "inverse_201704",
            "standard", "standard_2017", "standard_201704",
        ]
        assert list(f.view("standard").fragments[0].row(1)) == [9]
        assert list(f.view("inverse").fragments[0].row(9)) == [1]
    finally:
        f.close()


def test_holder_roundtrip(tmp_path):
    h = Holder(str(tmp_path))
    h.open()
    idx = h.create_index("myidx")
    fr = idx.create_frame("myframe", inverse_enabled=True)
    fr.set_bit(10, 20)
    fr.row_attr_store.set_attrs(10, {"tag": "x"})
    h.close()

    h2 = Holder(str(tmp_path))
    h2.open()
    try:
        fr2 = h2.frame("myidx", "myframe")
        assert fr2 is not None
        assert fr2.inverse_enabled
        assert list(fr2.view("standard").fragments[0].row(10)) == [20]
        assert fr2.row_attr_store.attrs(10) == {"tag": "x"}
        assert h2.schema()[0]["name"] == "myidx"
        frag = h2.fragment("myidx", "myframe", "standard", 0)
        assert frag is not None and frag.count() == 1
    finally:
        h2.close()


def test_holder_cold_open_is_lazy(tmp_path, monkeypatch):
    """Reopening a data dir must not parse any fragment file (O(schema)
    cold start, the mmap-attach analog, reference fragment.go:211-229);
    the first touch loads, and Holder.warm loads the rest."""
    h = Holder(str(tmp_path))
    h.open()
    idx = h.create_index("i")
    fr = idx.create_frame("f")
    for s in range(4):
        fr.set_bit(1, s * SLICE_WIDTH + 3)
    h.close()

    import pilosa_tpu.core.fragment as fragment_mod

    calls = {"n": 0}
    orig = fragment_mod.Bitmap.from_bytes

    def counting(data, **kw):
        calls["n"] += 1
        return orig(data, **kw)

    monkeypatch.setattr(fragment_mod.Bitmap, "from_bytes",
                        staticmethod(counting))
    h2 = Holder(str(tmp_path))
    h2.open()
    try:
        assert calls["n"] == 0  # nothing parsed at open
        assert len(h2.frame("i", "f").view("standard").fragments) == 4
        # First touch parses exactly that fragment.
        assert h2.fragment("i", "f", "standard", 2).count() == 1
        assert calls["n"] == 1
        # Background warm loads the rest; flush_cache on never-loaded
        # fragments must not force a parse either.
        h2.flush_caches()
        assert calls["n"] == 1
        h2.warm()
        assert calls["n"] == 4
        assert h2.fragment("i", "f", "standard", 0).count() == 1
    finally:
        h2.close()


def test_fragment_reopen_reattaches_wal(tmp_path):
    """open → write → close → open on the SAME Fragment object must
    re-parse and re-attach the WAL: writes after the reopen have to be
    durable (a stale loaded flag would leave op_writer detached)."""
    from pilosa_tpu.core.fragment import Fragment

    path = str(tmp_path / "frag")
    f = Fragment(path, "i", "f", "standard", 0)
    f.open()
    f.set_bit(1, 10)
    f.close()
    f.open()
    assert f.storage.op_writer is not None
    f.set_bit(2, 20)  # must reach the WAL
    f.close()

    g = Fragment(path, "i", "f", "standard", 0)
    g.open()
    try:
        assert g.count() == 2
        assert list(g.row(2)) == [20]
    finally:
        g.close()


def test_lazy_corrupt_fragment_raises_on_every_touch(tmp_path):
    """A corrupt storage file under lazy open must raise on EVERY touch
    — never degrade to a silently-empty fragment whose next snapshot
    would overwrite the real data."""
    h = Holder(str(tmp_path))
    h.open()
    h.create_index("i").create_frame("f").set_bit(1, 2)
    h.close()

    frag_path = tmp_path / "i" / "f" / "standard" / "fragments" / "0"
    data = bytearray(frag_path.read_bytes())
    data[0] ^= 0xFF  # break the cookie
    frag_path.write_bytes(bytes(data))

    h2 = Holder(str(tmp_path))
    h2.open()  # lazy: corruption not seen yet
    try:
        frag = h2.fragment("i", "f", "standard", 0)
        with pytest.raises(Exception):
            frag.count()
        with pytest.raises(Exception):  # still pending, still loud
            frag.set_bit(3, 4)
        h2.warm()  # must survive the bad fragment (logged, not fatal)
    finally:
        h2.close()


def test_frame_import_with_inverse(tmp_path):
    f = Frame(str(tmp_path / "f"), "i", "f", inverse_enabled=True)
    f.open()
    try:
        f.import_bits([1, 1, 2], [5, SLICE_WIDTH + 6, 7])
        std = f.view("standard")
        assert sorted(std.fragments) == [0, 1]
        assert list(std.fragments[1].row(1)) == [SLICE_WIDTH + 6]
        inv = f.view("inverse")
        assert list(inv.fragments[0].row(5)) == [1]
    finally:
        f.close()


def test_index_frame_validation(tmp_path):
    h = Holder(str(tmp_path))
    h.open()
    try:
        with pytest.raises(ValueError):
            h.create_index("Bad_Name")
        idx = h.create_index("ok")
        with pytest.raises(ValueError):
            idx.create_frame("9bad")
        idx.create_frame("fine")
        with pytest.raises(FrameExistsError):
            idx.create_frame("fine")
    finally:
        h.close()


def test_views_by_time_range_month_end_start():
    # day-31 start crossing shorter months must normalize, not raise
    got = views_by_time_range("F", datetime(2017, 1, 31), datetime(2017, 6, 1),
                              TimeQuantum("YMD"))
    assert got[0] == "F_20170131"
    assert "F_201702" in got or any(v.startswith("F_201702") for v in got)


def test_row_result_does_not_alias_source():
    r1 = Row([5])
    u = r1.union(Row())
    u.set_bit(6)
    assert list(r1) == [5]
    d = r1.difference(Row([999]))
    d.set_bit(7)
    assert list(r1) == [5]
    m = Row()
    m.merge(r1)
    m.set_bit(8)
    assert list(r1) == [5]


# -- regression: review findings --------------------------------------------

def test_fragment_blocks_sparse_huge_row(frag):
    """blocks() must visit only blocks with live containers — a single bit
    at a huge rowID must not scan the dense block range."""
    frag.set_bit(2**34, 5)
    frag.set_bit(1, 7)
    blocks = frag.blocks()  # must return promptly
    containers_per_block = 100 * SLICE_WIDTH >> 16
    expected_blocks = {(2**34 * 16) // containers_per_block,
                       (1 * 16) // containers_per_block}
    assert {b for b, _ in blocks} == expected_blocks


def test_fragment_corrupt_cache_file_rebuilds(tmp_path):
    path = str(tmp_path / "0")
    f = Fragment(path, "i", "f", "standard", 0)
    f.open()
    f.set_bit(2, 1)
    f.set_bit(2, 3)
    f.set_bit(5, 1)
    f.close()
    # Simulate crash mid-flush: truncated JSON.
    with open(path + ".cache", "w") as fh:
        fh.write('[[2, ')
    f2 = Fragment(path, "i", "f", "standard", 0)
    f2.open()
    try:
        assert f2.top(TopOptions(n=10)) == [(2, 2), (5, 1)]
    finally:
        f2.close()


def test_fragment_top_requested_ids_exact_after_clear(frag):
    """Explicitly requested row ids must be recounted exactly, not served
    from the threshold-gated rank cache (which never records zero)."""
    frag.set_bit(2, 1)
    frag.cache.recalculate()  # threshold_value becomes 1
    frag.clear_bit(2, 1)      # cache.add(2, 0) is gated out
    assert frag.top(TopOptions(row_ids=[2])) == []


class TestPairIterators:
    """core/iterator.py — the (row,col) pair iterator compat seam
    (reference iterator.go:24-194)."""

    def _slice_it(self):
        import numpy as np
        from pilosa_tpu.core.iterator import SliceIterator
        rows = np.array([2, 0, 1, 0, 1], dtype=np.uint64)
        cols = np.array([9, 5, 1, 3, 8], dtype=np.uint64)
        return SliceIterator(rows, cols)

    def test_slice_iterator_sorted_order(self):
        assert list(self._slice_it()) == [(0, 3), (0, 5), (1, 1), (1, 8),
                                          (2, 9)]

    def test_slice_iterator_seek(self):
        it = self._slice_it()
        it.seek(1, 2)
        assert it.next() == (1, 8)
        it.seek(0, 0)
        assert it.next() == (0, 3)
        it.seek(3, 0)
        assert it.next() is None

    def test_roaring_iterator_divmod(self):
        from pilosa_tpu import SLICE_WIDTH
        from pilosa_tpu.core.iterator import RoaringIterator
        from pilosa_tpu.roaring import Bitmap
        b = Bitmap([3, SLICE_WIDTH + 7, 2 * SLICE_WIDTH])
        it = RoaringIterator(b)
        assert list(it) == [(0, 3), (1, 7), (2, 0)]
        it.seek(1, 0)
        assert it.next() == (1, 7)

    def test_buf_iterator_unread_peek(self):
        from pilosa_tpu.core.iterator import BufIterator
        it = BufIterator(self._slice_it())
        assert it.peek() == (0, 3)
        assert it.next() == (0, 3)   # peek did not consume
        assert it.next() == (0, 5)
        it.unread()
        assert it.next() == (0, 5)   # unread replays
        with_pairs = list(it)
        assert with_pairs == [(1, 1), (1, 8), (2, 9)]

    def test_limit_iterator(self):
        from pilosa_tpu.core.iterator import LimitIterator
        assert list(LimitIterator(self._slice_it(), 2)) == [(0, 3), (0, 5)]


class TestConcurrency:
    """Thread-safety of the storage tree under the threaded HTTP server
    model (reference Fragment.mu / Holder.mu)."""

    def test_concurrent_setbits_one_fragment(self, tmp_path):
        import threading

        from pilosa_tpu.core import Fragment

        frag = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
        frag.open()
        try:
            n_threads, per_thread = 8, 400

            def worker(t):
                for i in range(per_thread):
                    frag.set_bit(t % 4, t * per_thread + i)
                    if i % 50 == 0:
                        frag.row(t % 4).count()

            ts = [threading.Thread(target=worker, args=(t,))
                  for t in range(n_threads)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert frag.count() == n_threads * per_thread
            assert not frag.storage.check()
        finally:
            frag.close()
        # WAL + snapshot survived interleaving: reopen agrees.
        frag2 = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
        frag2.open()
        try:
            assert frag2.count() == n_threads * per_thread
        finally:
            frag2.close()

    def test_concurrent_create_if_not_exists(self, tmp_path):
        import threading

        from pilosa_tpu.core import Holder

        holder = Holder(str(tmp_path / "h"))
        holder.open()
        try:
            results = []

            def worker():
                idx = holder.create_index_if_not_exists("i")
                f = idx.create_frame_if_not_exists("f")
                v = f.create_view_if_not_exists("standard")
                frag = v.create_fragment_if_not_exists(0)
                results.append((id(idx), id(f), id(v), id(frag)))

            ts = [threading.Thread(target=worker) for _ in range(16)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            # Every thread observed the SAME objects — no clobbered
            # duplicates from check-then-act races.
            assert len(set(results)) == 1
        finally:
            holder.close()
