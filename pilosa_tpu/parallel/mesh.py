"""Device-mesh execution: slices sharded across TPU devices, reductions
over ICI collectives.

This is the TPU-native replacement for the reference's cluster mapReduce
(executor.go:1103-1163): instead of HTTP fan-out + coordinator merge,
all slices of an index live stacked in HBM across a
`jax.sharding.Mesh`, one shard_map'd computation evaluates the query on
every device's local slices, and Count / per-row totals reduce with
`lax.psum` over the mesh axis (ICI), never leaving the device fabric.

Layout: a ShardedIndex stacks per-slice FragmentPools into
  keys  (S, C)        int32   — C = max container capacity over slices
  words (S, C, 2048)  uint32  — bitmap-form containers
sharded on the leading (slice) axis. Container keys use GLOBAL dense row
indices (one row-id table for the whole index), so a row's dense index is
the same on every shard and query row-lookups broadcast as scalars.

TopN here is EXACT: per-row popcounts segment-summed on every shard,
psum'd over the mesh, then a replicated lax.top_k — no rank-cache
approximation pass (closes the reference's two-phase TopN refetch,
executor.go:273-310, with one collective).
"""

from __future__ import annotations

import json
from functools import partial
from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exports shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # 0.4.x: experimental home, and the replication
    # lint is check_rep, not check_vma. Run with the lint OFF: 0.4.x
    # check_rep raises spurious errors on patterns the VMA checker
    # accepts (scan carries of shard-local values), and the lint has
    # no runtime semantics.
    from jax.experimental.shard_map import shard_map as _shard_map_04

    def shard_map(f, /, *, check_vma=None, **kw):
        kw.setdefault("check_rep", False)
        return _shard_map_04(f, **kw)

try:  # jax >= 0.7: varying-manual-axes marker for shard_map carries
    _pcast = jax.lax.pcast
except AttributeError:  # older jax: no VMA checker, marking is a no-op
    def _pcast(x, axes, to=None):
        return x

from .. import SLICE_WIDTH
from ..obs import get_logger, profile
from ..obs import span as obs_span
from ..ops.pool import CONTAINER_WORDS, INVALID_KEY, ROW_SPAN, FragmentPool
from .plan import _tree_signature, eval_tree

SLICE_AXIS = "slices"


def slice_device(slice_: int, num_slices: int, n_devices: int) -> int:
    """Which mesh device serves a slice under the P(SLICE_AXIS)
    sharding every staged pool uses: the slice axis pads to a multiple
    of the device count (build_sharded_index / build_sparse_sharded)
    and NamedSharding splits it into contiguous chunks — a CONSISTENT
    placement across every view of an index at a given slice count.
    Because a slice holds every row of its view — all BSI magnitude
    planes, the existence row, the sign row — any per-row/ per-plane
    combination is device-local by construction; only count partials
    ever cross the interconnect (psum). Placement moves ONLY when the
    padded slice count changes (index growth past a pad boundary or a
    mesh resize), which forces a restage anyway."""
    n_dev = max(1, int(n_devices))
    s_pad = -(-max(1, int(num_slices)) // n_dev) * n_dev
    return int(slice_) // (s_pad // n_dev)


class ShardedIndex(NamedTuple):
    """One frame/view's fragments, stacked and mesh-sharded."""

    keys: jax.Array   # (S, C) int32, INVALID_KEY padded
    words: jax.Array  # (S, C, CONTAINER_WORDS) uint32

    @property
    def num_slices(self) -> int:
        return self.keys.shape[0]

    @property
    def capacity(self) -> int:
        return self.keys.shape[1]


def _stage_chunk_bytes() -> int:
    """H2D staging chunk size (PILOSA_TPU_STAGE_CHUNK_MB env, default
    64 MB): below the chunk size a shard moves as ONE device_put;
    above it, as a pipeline of chunk-sized device_puts with host
    packing double-buffered against the in-flight transfer
    (_stage_pipeline). The old 1024 MB default meant every sub-GB
    shard took the single-put path — zero pipelining, pack time and
    transfer time strictly serial, the shape of the r5b 0.0094 GB/s
    staging floor. 64 MB is small enough that typical shards cut into
    several chunks (the headline ~1 GB pool: 16) and large enough
    that per-put dispatch overhead stays < 1% of a chunk's transfer
    at PCIe/ICI rates."""
    import os

    try:
        mb = int(os.environ.get("PILOSA_TPU_STAGE_CHUNK_MB", "64"))
    except ValueError:
        mb = 64
    return max(1, mb) << 20


def _stage_pipeline(pack_range, ranges, dev, on_chunk=None):
    """Pipelined chunk transfers for one shard: pack || transfer.

    ranges is the ordered [lo, hi) chunk list. A producer thread packs
    chunk i+1 while chunk i's device_put dispatches and its async
    transfer streams; because device_put never blocks, the in-flight
    transfers additionally overlap device EXECUTION of already-resident
    work (bench's staging_bandwidth section proves the overlap via the
    stage_h2d/device_exec profile phases). The queue depth bounds host
    memory at two packed-but-unshipped chunks. A single-chunk shard
    skips the thread — no pipeline exists to win there.

    on_chunk(nbytes) fires after each chunk's put dispatches: the
    per-chunk cumulative byte accounting (every chunk counts toward
    bytes_staged, not just the final one). Returns the device pieces
    in range order; a pack error re-raises here, a device_put error
    propagates with the producer thread parked (daemon, bounded by the
    queue) for the fallback path to proceed past."""
    if len(ranges) == 1:
        host = pack_range(*ranges[0])
        piece = jax.device_put(host, dev)
        if on_chunk is not None:
            on_chunk(host.nbytes)
        return [piece]
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=2)

    def produce():
        try:
            for lo, hi in ranges:
                q.put(("ok", pack_range(lo, hi)))
        except BaseException as e:  # noqa: BLE001 — surfaced on the
            # consumer side; the packer must not die silently
            q.put(("err", e))

    threading.Thread(target=produce, daemon=True, name="h2d-pack").start()
    from ..obs.health import HEALTH

    # Visibility-only bracket (base=None): staging time scales with
    # the slab, so the watchdog never judges it — but a wedged
    # device_put shows this thread pinned in /debug/health.
    with HEALTH.inflight("h2d-pack", "stage"):
        pieces = []
        for _ in ranges:
            tag, payload = q.get()
            if tag == "err":
                raise payload
            pieces.append(jax.device_put(payload, dev))
            if on_chunk is not None:
                on_chunk(payload.nbytes)
    return pieces


_FOLD_CHUNK = None


def _fold_chunk_fn():
    """Jitted donated dynamic_update_slice: folds one transferred chunk
    into the shard buffer IN PLACE (donation), so chunked assembly
    peaks at shard + one chunk of HBM — a jnp.concatenate would
    transiently hold shard + all chunks (2x the pool). CPU backends
    don't implement donation; the fallback copy is fine at test scale."""
    global _FOLD_CHUNK
    if _FOLD_CHUNK is None:
        donate = (0,) if jax.default_backend() != "cpu" else ()
        _FOLD_CHUNK = jax.jit(
            lambda buf, piece, off: lax.dynamic_update_slice(
                buf, piece, (off, 0, 0)),
            donate_argnums=donate)
    return _FOLD_CHUNK


def _assemble_shard(pieces: List, offs: List[int], shard_shape, dev):
    """One device shard from its transferred chunk pieces."""
    if len(pieces) == 1:
        return pieces[0]
    import contextlib

    ctx = jax.default_device(dev) if dev is not None \
        else contextlib.nullcontext()
    with ctx:
        buf = jnp.zeros(shard_shape, dtype=jnp.uint32)
    fold = _fold_chunk_fn()
    for p, off in zip(pieces, offs):
        buf = fold(buf, p, np.int32(off))
    return buf


def build_sharded_index(bitmaps: Sequence, mesh: Optional[Mesh] = None,
                        capacity: Optional[int] = None,
                        with_host_keys: bool = False,
                        stats_out: Optional[dict] = None,
                        row_ids: Optional[np.ndarray] = None):
    """Stack per-slice host bitmaps into a ShardedIndex.

    bitmaps[s] is the slice-s roaring Bitmap (or None for an absent
    fragment). Returns (ShardedIndex, row_ids): row_ids is the GLOBAL
    sorted uint64 row-id table shared by all shards. The slice count is
    padded up to a multiple of the mesh axis size. with_host_keys=True
    appends the packed (S_padded, cap) int32 numpy keys to the return —
    consumers needing them must take this copy, NOT np.asarray the
    device keys, which fails on a multi-process mesh (non-addressable
    shards).

    Staging is the cold-start hard part (SURVEY §7: the reference gets
    O(1) open via mmap, fragment.go:211-229; a device needs explicit
    H2D). Three levers here:
      - words are packed PER ADDRESSABLE SHARD and device_put straight
        to the owning device (no whole-pool transfer to device 0 and
        re-distribution — on a multi-host mesh each process packs and
        ships only its own slices);
      - each shard moves as a pipeline of chunk-sized device_puts
        (_stage_chunk_bytes, default 64 MB) with a dedicated packer
        thread (_stage_pipeline): chunk i+1 packs WHILE chunk i's
        transfer streams, so the wall cost approaches
        max(pack, transfer) instead of their sum;
      - nothing blocks on completion: the returned arrays are async
        futures and the first query's compile proceeds while the
        transfer streams — in-flight chunks also overlap device
        execution of already-resident work. stats_out (if given) gets
        the host-side dispatch seconds, byte counts, and the
        chunk-count proof of which path ran, for /debug/vars.
    """
    import time as _time

    n_dev = mesh.shape[SLICE_AXIS] if mesh is not None else 1
    s = max(1, len(bitmaps))
    s_pad = -(-s // n_dev) * n_dev

    # Global dense row table — injectable (row_ids=) so a dual-format
    # stager can number rows over ALL slices once and hand both the
    # dense and the sparse pool the same table (a per-pool np.unique
    # would give the two pools different dense indices for one row).
    if row_ids is None:
        all_rows = [np.asarray(b.keys, dtype=np.uint64) >> np.uint64(4)
                    for b in bitmaps if b is not None and len(b.keys)]
        row_ids = (np.unique(np.concatenate(all_rows)) if all_rows
                   else np.empty(0, dtype=np.uint64))

    counts = [len(b.keys) if b is not None else 0 for b in bitmaps]
    # capacity=0 is an explicit "no dense containers anywhere" (a pure
    # sparse-format view staging an empty dense pool so every consumer
    # of sv.sharded keeps a real array to hold on to).
    cap = capacity if capacity is not None else max(1, max(counts,
                                                           default=1))
    # Round capacity up to a ROW_SPAN multiple: the coarse-gather
    # serving programs view the pool as (S, cap/16, 16*W) whole-row
    # runs, which needs 16 | cap. Cost: < 16 padded containers/slice.
    cap = -(-cap // ROW_SPAN) * ROW_SPAN

    t0 = _time.monotonic()
    h2d_sp = obs_span("h2d", slices=s_pad)
    h2d_ph = profile.phase("stage_h2d").start()
    # Keys (small, s_pad*cap*4 B) pack fully on every host; the sorted
    # container order is kept for the words pack below.
    keys = np.full((s_pad, cap), INVALID_KEY, dtype=np.int32)
    orders: List[Optional[np.ndarray]] = [None] * s_pad
    for si, b in enumerate(bitmaps):
        if b is None or not len(b.keys):
            continue
        real = np.asarray(b.keys, dtype=np.uint64)
        dense = np.searchsorted(row_ids, real >> np.uint64(4))
        k = (dense * ROW_SPAN
             + (real & np.uint64(15)).astype(np.int64)).astype(np.int32)
        order = np.argsort(k)
        keys[si, : len(k)] = k[order]
        orders[si] = order

    def pack_range(lo: int, hi: int) -> np.ndarray:
        buf = np.zeros((hi - lo, cap, CONTAINER_WORDS), dtype=np.uint32)
        for si in range(lo, min(hi, len(bitmaps))):
            order = orders[si]
            if order is None:
                continue
            b = bitmaps[si]
            row = buf[si - lo]
            for j, ci in enumerate(order):
                row[j] = b.containers[ci].words().view(np.uint32)
        return buf

    slice_bytes = cap * CONTAINER_WORDS * 4
    chunk_slices = max(1, _stage_chunk_bytes() // max(1, slice_bytes))
    h2d_bytes = 0
    h2d_chunks = 0

    def on_chunk(nbytes: int) -> None:
        # Cumulative per-chunk accounting AS chunks dispatch — a
        # mid-stage profile dump (or an exception between chunks)
        # reports the bytes actually shipped, and the chunk count
        # proves which path (pipelined vs single-put) ran.
        nonlocal h2d_bytes, h2d_chunks
        h2d_bytes += nbytes
        h2d_chunks += 1
        profile.add_bytes("bytes_staged", nbytes)

    def chunk_ranges(lo: int, hi: int):
        return [(c, min(c + chunk_slices, hi))
                for c in range(lo, hi, chunk_slices)]

    if mesh is None:
        ranges = chunk_ranges(0, s_pad)
        pieces = _stage_pipeline(pack_range, ranges, None, on_chunk)
        words_arr = _assemble_shard(
            pieces, [r[0] for r in ranges],
            (s_pad, cap, CONTAINER_WORDS), None)
        keys_arr = jnp.asarray(keys)
    else:
        sharding = NamedSharding(mesh, P(SLICE_AXIS))
        shape = (s_pad, cap, CONTAINER_WORDS)
        try:
            imap = sharding.addressable_devices_indices_map(shape)
            shards = []
            for dev, idxs in imap.items():
                lo = idxs[0].start or 0
                hi = idxs[0].stop if idxs[0].stop is not None else s_pad
                ranges = chunk_ranges(lo, hi)
                pieces = _stage_pipeline(pack_range, ranges, dev,
                                         on_chunk)
                shards.append(_assemble_shard(
                    pieces, [c - lo for c, _ in ranges],
                    (hi - lo, cap, CONTAINER_WORDS), dev))
            words_arr = jax.make_array_from_single_device_arrays(
                shape, sharding, shards)
        except Exception as fb_err:  # noqa: BLE001 — backend without
            # per-device placement support (untested relay backends):
            # fall back to the whole-pool transfer + redistribution
            # path (one host pack of the full pool — device_put with a
            # global sharding needs the whole array per process
            # anyway). Slower, and host-RAM-bound at extreme pool
            # sizes, but always works. Drop the partial attempt's
            # device buffers FIRST: keeping them across the second full
            # transfer would stack partial + whole pool in HBM. Loudly
            # recorded — a silent fallback would read as a mysterious
            # staging regression.
            get_logger("mesh").warning(
                "per-device staging failed (%s: %s); falling back to "
                "whole-pool placement", type(fb_err).__name__, fb_err)
            if stats_out is not None:
                stats_out["h2d_fallback"] = f"{type(fb_err).__name__}: " \
                                            f"{fb_err}"
            shards = pieces = None  # noqa: F841 — release device refs
            words_arr = jax.device_put(pack_range(0, s_pad), sharding)
            # on_chunk: chunks shipped before the failure were real
            # traffic and already counted; the whole-pool retry adds
            # its own bytes on top.
            on_chunk(s_pad * slice_bytes)
        keys_arr = jax.device_put(keys, sharding)
    if stats_out is not None:
        stats_out["h2d_dispatch_s"] = _time.monotonic() - t0
        stats_out["h2d_bytes"] = h2d_bytes + keys.nbytes
        stats_out["h2d_chunk_slices"] = chunk_slices
        stats_out["h2d_chunks"] = h2d_chunks
    h2d_sp.tag(h2d_bytes=h2d_bytes + keys.nbytes,
               chunk_slices=chunk_slices, chunks=h2d_chunks).finish()
    h2d_ph.stop()
    profile.add_bytes("bytes_staged", keys.nbytes)
    idx = ShardedIndex(keys=keys_arr, words=words_arr)
    if with_host_keys:
        return idx, row_ids, keys
    return idx, row_ids


# -- sparsity-adaptive staging: sorted-array (roaring array) device pools -----
#
# The dense image bills 8 KB of HBM per container regardless of
# cardinality; a 3%-density container carries ~2 K values = 4 KB live,
# and a 0.3% one ~200 values = 400 B — 20-2000x padding waste. The
# roaring container taxonomy (arXiv:1709.07821 §2.1: array below 4096
# values, bitmap above) applied at STAGING time: slices whose mean
# container fill sits under a density threshold stage as sorted u16
# value arrays + a cardinality table, everything else keeps packed
# words. One staged view can hold BOTH pools (mixed views), with a
# per-slice format byte deciding which pool serves each slice.

# A container with more than 4096 values is smaller as a bitmap
# (4096 * 2 B = 8 KB = the packed-word size) — the reference's
# ARRAY_MAX_SIZE break-even (roaring.go:951,1023).
ARRAY_VALUE_CAP = 4096

# Sparse eligibility floor: a slice whose TOTAL cardinality is under
# this never stages as sorted arrays. Below it the whole slice is
# Kbyte-scale either way, and the sparse path's extra host metadata
# resolution + separate kernel dispatch cost more than the HBM it
# saves. It also keeps tiny working sets (unit fixtures, cold frames)
# on the one-format dense path the batch/coarse dispatchers are
# specialized for.
SPARSE_MIN_SLICE_CARD = 1024

# Sparse value-capacity alignment: K pads to a lane multiple so the
# Pallas broadcast-compare kernel and the (8, 128)-tiled gathers see
# full tiles.
_VALUE_ALIGN = 128


class SparseShardedIndex(NamedTuple):
    """One frame/view's SPARSE slices: sorted-array containers, stacked
    and mesh-sharded. Same key packing as ShardedIndex (global dense
    row * 16 + subkey, INVALID_KEY padded) so the host row-resolution
    machinery (resolve_row_indices) works unchanged on either pool."""

    keys: jax.Array    # (S, C) int32, INVALID_KEY padded
    values: jax.Array  # (S, C, K) uint16, sorted, 0xFFFF padded
    cards: jax.Array   # (S, C) int32 real cardinalities

    @property
    def num_slices(self) -> int:
        return self.keys.shape[0]

    @property
    def capacity(self) -> int:
        return self.keys.shape[1]

    @property
    def value_cap(self) -> int:
        return self.values.shape[2]


def slice_format_stats(bitmaps: Sequence) -> np.ndarray:
    """Per-slice container stats the format pick runs on: (S, 3) int64
    [n_containers, total_cardinality, max_cardinality]. Uses the host
    Container.n the stager already has — no container is materialized
    to words to decide its format."""
    out = np.zeros((len(bitmaps), 3), dtype=np.int64)
    for si, b in enumerate(bitmaps):
        if b is None or not len(b.keys):
            continue
        ns = [c.n for c in b.containers]
        out[si] = (len(ns), sum(ns), max(ns))
    return out


def pick_slice_formats(stats: np.ndarray, threshold: float,
                       prev: Optional[np.ndarray] = None,
                       band: float = 1.25,
                       value_cap: int = ARRAY_VALUE_CAP,
                       min_card: int = SPARSE_MIN_SLICE_CARD) -> np.ndarray:
    """Per-slice format decision: 1 = sorted-array, 0 = packed words.

    A slice goes sparse when its mean container fill
    (total_card / (n_containers * 65536)) is under `threshold`, its
    total cardinality is at least `min_card` (below that the slice is
    Kbyte-scale either way and the sparse dispatch overhead wins), AND
    no container exceeds `value_cap` values (beyond 4096 the array
    form is LARGER than the bitmap — the reference's ARRAY_MAX_SIZE
    break-even). threshold <= 0 is the kill switch: everything dense.

    Hysteresis: with `prev` (the view's formats before a restage), a
    slice keeps its previous format inside the [threshold/band,
    threshold*band) window, so a fragment sitting near the boundary
    does not flip layout — and pay a full repack — on every
    incremental refresh. Crossing the far edge of the band always
    converts."""
    s = stats.shape[0]
    n = stats[:, 0].astype(np.float64)
    total = stats[:, 1].astype(np.float64)
    density = np.where(n > 0, total / np.maximum(n, 1) / 65536.0, 1.0)
    eligible = ((stats[:, 0] > 0) & (stats[:, 2] <= value_cap)
                & (stats[:, 1] >= min_card))
    if threshold <= 0:
        return np.zeros(s, dtype=np.uint8)
    fmt = (eligible & (density < threshold)).astype(np.uint8)
    if prev is not None and band > 1.0:
        m = min(s, len(prev))
        was_sparse = prev[:m].astype(bool)
        keep_sparse = was_sparse & eligible[:m] & (
            density[:m] < threshold * band)
        go_sparse = ~was_sparse & eligible[:m] & (
            density[:m] < threshold / band)
        fmt[:m] = (keep_sparse | go_sparse).astype(np.uint8)
    return fmt


def split_bitmaps_by_format(bitmaps: Sequence, formats: np.ndarray):
    """(dense_list, sparse_list): each the full-length slice list with
    the other format's slices None — the shape the two builders eat."""
    dense = [b if not formats[si] else None for si, b in enumerate(bitmaps)]
    sparse = [b if formats[si] else None for si, b in enumerate(bitmaps)]
    return dense, sparse


def global_row_ids(bitmaps: Sequence) -> np.ndarray:
    """The GLOBAL sorted uint64 row-id table over every slice — shared
    by the dense and sparse pools of one view (see build_sharded_index
    row_ids=)."""
    all_rows = [np.asarray(b.keys, dtype=np.uint64) >> np.uint64(4)
                for b in bitmaps if b is not None and len(b.keys)]
    return (np.unique(np.concatenate(all_rows)) if all_rows
            else np.empty(0, dtype=np.uint64))


def sparse_pool_dims(bitmaps: Sequence) -> Tuple[int, int]:
    """(container capacity C, value capacity K) of the sparse pool that
    build_sparse_sharded_index would stage for these slices — shared
    with the byte estimators so budget admission and actual staging
    cannot disagree."""
    counts = [len(b.keys) if b is not None else 0 for b in bitmaps]
    cap = max(1, max(counts, default=1))
    cap = -(-cap // ROW_SPAN) * ROW_SPAN
    max_card = 1
    for b in bitmaps:
        if b is None or not len(b.keys):
            continue
        max_card = max(max_card, max(c.n for c in b.containers))
    k = -(-max_card // _VALUE_ALIGN) * _VALUE_ALIGN
    return cap, k


def sparse_pool_bytes(num_slices: int, n_dev: int, cap: int,
                      k: int) -> int:
    """Padded HBM bytes of a (C=cap, K=k) sparse pool over num_slices
    slices on an n_dev mesh axis: values u16 + keys i32 + cards i32."""
    s_pad = -(-max(1, num_slices) // n_dev) * n_dev
    return s_pad * cap * (k * 2 + 4 + 4)


def build_sparse_sharded_index(bitmaps: Sequence,
                               mesh: Optional[Mesh] = None,
                               row_ids: Optional[np.ndarray] = None,
                               stats_out: Optional[dict] = None):
    """Stack the SPARSE slices' bitmaps into a SparseShardedIndex.

    bitmaps[s] is the slice-s roaring Bitmap for sparse-format slices
    and None elsewhere (dense or absent) — full-length, so slice
    positions line up with the dense pool. Containers pack as sorted
    u16 value arrays (Container.values(), already sorted) padded to
    the pool-wide value capacity with 0xFFFF; keys pack exactly like
    the dense builder so resolve_row_indices works on the host copy.

    Returns (SparseShardedIndex, row_ids, keys_host, cards_host) —
    the host keys/cards copies are always produced (they are the
    serving metadata AND the live-byte accounting source; a sparse
    pool is small enough that the copies are noise).

    No chunk pipeline here: a sparse pool is 10-100x smaller than the
    dense image of the same slices (the whole point), so a plain
    sharded device_put is already under the pipelining break-even."""
    import time as _time

    n_dev = mesh.shape[SLICE_AXIS] if mesh is not None else 1
    s = max(1, len(bitmaps))
    s_pad = -(-s // n_dev) * n_dev

    if row_ids is None:
        row_ids = global_row_ids(bitmaps)
    cap, k = sparse_pool_dims(bitmaps)

    t0 = _time.monotonic()
    keys = np.full((s_pad, cap), INVALID_KEY, dtype=np.int32)
    values = np.full((s_pad, cap, k), 0xFFFF, dtype=np.uint16)
    cards = np.zeros((s_pad, cap), dtype=np.int32)
    for si, b in enumerate(bitmaps):
        if b is None or not len(b.keys):
            continue
        real = np.asarray(b.keys, dtype=np.uint64)
        dense = np.searchsorted(row_ids, real >> np.uint64(4))
        kk = (dense * ROW_SPAN
              + (real & np.uint64(15)).astype(np.int64)).astype(np.int32)
        order = np.argsort(kk)
        keys[si, : len(kk)] = kk[order]
        for j, ci in enumerate(order):
            vals = b.containers[ci].values()
            cards[si, j] = len(vals)
            values[si, j, : len(vals)] = vals.astype(np.uint16)

    if mesh is None:
        keys_arr = jnp.asarray(keys)
        values_arr = jnp.asarray(values)
        cards_arr = jnp.asarray(cards)
    else:
        sharding = NamedSharding(mesh, P(SLICE_AXIS))
        keys_arr = jax.device_put(keys, sharding)
        values_arr = jax.device_put(values, sharding)
        cards_arr = jax.device_put(cards, sharding)
    nbytes = values.nbytes + keys.nbytes + cards.nbytes
    profile.add_bytes("bytes_staged", nbytes)
    if stats_out is not None:
        stats_out["sparse_h2d_bytes"] = nbytes
        stats_out["sparse_h2d_dispatch_s"] = _time.monotonic() - t0
        stats_out["sparse_value_cap"] = k
    idx = SparseShardedIndex(keys=keys_arr, values=values_arr,
                             cards=cards_arr)
    return idx, row_ids, keys, cards


def _gather_sparse_containers(vals, cards, idx_l, hit_l):
    """One sparse leaf's row containers for the serving kernels:
    (S_l*16, K) values and HIT-ZEROED (S_l*16,) cardinalities, flat-
    gathered with host-resolved within-slice indices — the sorted-array
    counterpart of _gather_leaf_blocks. Zeroed cardinalities make every
    downstream kernel exact on absent containers (no valid a-positions,
    no valid b-positions, so intersections and op counts are 0)."""
    s_l, c, k = vals.shape
    base = (jnp.arange(s_l, dtype=jnp.int32) * c)[:, None]
    flat = (idx_l + base).reshape(-1)
    v = vals.reshape(s_l * c, k)[flat]
    n = cards.reshape(-1)[flat] * hit_l.reshape(-1).astype(jnp.int32)
    return v, n


def compile_serve_count_sparse_pair(mesh: Mesh, op: str, kind: str,
                                    backend: str = "xla",
                                    interpret: bool = False):
    """Jit a masked two-leaf Count where at least one leaf serves from
    a sorted-array pool — the device analog of the reference's
    per-container-type kernel table (roaring.go:1270-1351), dispatched
    per SLICE GROUP by the serving layer.

    kind: "ss" (both sparse — array×array intersect kernel),
          "sd" (leaf 0 sparse, leaf 1 dense — array×bitmap probe),
          "ds" (leaf 0 dense, leaf 1 sparse — probe, operands swapped
          back for the asymmetric ops).
    op:   "and" | "or" | "andnot" (the plan lowering's full op set);
          everything beyond intersection derives per container by
          inclusion–exclusion from |a∩b| and the hit-masked operand
          cardinalities (bitops.sparse_op_counts).
    backend: for "ss", which intersect kernel serves — "xla" (binary-
          search gather ladder) or "pallas" (broadcast-compare); the
          calibrated race winner. Probe kinds are XLA-only (the TPU has
          no per-lane dynamic gather to write a Pallas probe with).

    Returns fn(pool_a, pool_b, idx_a, hit_a, idx_b, hit_b, mask)
    -> (2,) [lo, hi] limbs (combine_count). A sparse pool argument is
    the (values, cards) tuple, a dense one is (words,); idx/hit are the
    REPLICATED host (S, 16) resolve_row_indices outputs against the
    POOL THE LEAF SERVES FROM, mask the (S,) slice-group mask (1 only
    on slices this format pair owns)."""
    from ..ops.bitops import (sparse_op_counts,
                              sparse_pair_intersect_counts,
                              sparse_probe_intersect_counts)

    assert kind in ("ss", "sd", "ds"), kind

    def gather_dense(words, idx_l, hit_l):
        blk = _gather_leaf_blocks((words,), (idx_l,), (hit_l,), 0)
        return blk, lax.population_count(blk).astype(jnp.int32).sum(
            axis=-1)

    def per_shard(pool_a, pool_b, idx_a, hit_a, idx_b, hit_b, mask):
        s_l = pool_a[0].shape[0]
        off = lax.axis_index(SLICE_AXIS) * s_l
        ia = lax.dynamic_slice_in_dim(idx_a, off, s_l, axis=0)
        ha = lax.dynamic_slice_in_dim(hit_a, off, s_l, axis=0)
        ib = lax.dynamic_slice_in_dim(idx_b, off, s_l, axis=0)
        hb = lax.dynamic_slice_in_dim(hit_b, off, s_l, axis=0)
        mask_l = lax.dynamic_slice_in_dim(mask, off, s_l, axis=0)

        if kind == "ss":
            va, na = _gather_sparse_containers(pool_a[0], pool_a[1],
                                               ia, ha)
            vb, nb = _gather_sparse_containers(pool_b[0], pool_b[1],
                                               ib, hb)
            if backend == "pallas":
                from ..ops.kernels import pallas_sparse_pair_counts

                inter = pallas_sparse_pair_counts(va, na, vb, nb,
                                                  interpret=interpret)
            else:
                inter = sparse_pair_intersect_counts(va, na, vb, nb)
        elif kind == "sd":
            va, na = _gather_sparse_containers(pool_a[0], pool_a[1],
                                               ia, ha)
            blk, nb = gather_dense(pool_b[0], ib, hb)
            inter = sparse_probe_intersect_counts(va, na, blk)
        else:  # ds: probe the sparse side into the dense words;
            # |a∩b| is symmetric, na/nb keep their leaf positions so
            # andnot stays leaf0 - intersection.
            blk, na = gather_dense(pool_a[0], ia, ha)
            vb, nb = _gather_sparse_containers(pool_b[0], pool_b[1],
                                               ib, hb)
            inter = sparse_probe_intersect_counts(vb, nb, blk)

        counts = sparse_op_counts(op, inter, na, nb)
        per_slice = counts.reshape(s_l, ROW_SPAN).sum(
            axis=1).astype(jnp.uint32)
        per_slice = jnp.where(mask_l != 0, per_slice, jnp.uint32(0))
        lo = lax.psum(
            (per_slice & jnp.uint32(0xFFFF)).astype(jnp.int32).sum(),
            SLICE_AXIS)
        hi = lax.psum((per_slice >> 16).astype(jnp.int32).sum(),
                      SLICE_AXIS)
        return jnp.stack([lo, hi])

    pool_spec_a = (P(SLICE_AXIS),) if kind == "ds" else (
        P(SLICE_AXIS), P(SLICE_AXIS))
    pool_spec_b = (P(SLICE_AXIS),) if kind == "sd" else (
        P(SLICE_AXIS), P(SLICE_AXIS))
    fn = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(pool_spec_a, pool_spec_b, P(), P(), P(), P(), P()),
        out_specs=P(),
        check_vma=(backend == "xla"),
    )

    @jax.jit
    def run(pool_a, pool_b, idx_a, hit_a, idx_b, hit_b, mask):
        return fn(pool_a, pool_b, idx_a, hit_a, idx_b, hit_b, mask)

    return run


def _local_pools(keys, words):
    """Vmap helper: treat each local slice as a FragmentPool."""
    return FragmentPool(keys=keys, words=words, n=jnp.int32(0))


# Shared per-slice kernels — the compile_mesh_* entry points and the fused
# compile_mesh_step all build from these, so the standalone kernels and
# the fused step cannot drift apart.

def _count_one_slice(tree, num_leaves, keys, words, idxs):
    """Fused tree-eval + popcount for one slice's pool.

    int32: a global count saturates at 2^31-1 set bits (~2.1B); the JAX
    default config has no device int64. Callers needing beyond that
    aggregate per-slice counts host-side in Python ints."""
    pool = _local_pools(keys, words)
    leaves = tuple((pool, idxs[i]) for i in range(num_leaves))
    blk = eval_tree(tree, leaves)
    return lax.population_count(blk).astype(jnp.int32).sum()


def _row_counts_one_slice(num_rows, keys, words):
    """Per-dense-row popcounts for one slice's pool (segment-sum by
    key >> 4)."""
    per_container = lax.population_count(words).sum(axis=1, dtype=jnp.int32)
    valid = keys != INVALID_KEY
    dense = jnp.where(valid, keys // ROW_SPAN, num_rows)
    return jax.ops.segment_sum(
        jnp.where(valid, per_container, 0), dense,
        num_segments=num_rows + 1)[:num_rows]


def _apply_writes_one_slice(words, slot, word, mask):
    """Scatter a planned write batch into one slice's words.

    Scatter-max, not scatter-set: padding entries are (slot=0, mask=0)
    no-ops that may collide with a real write's target, and
    set-with-duplicates keeps an arbitrary one. cur|mask >= cur
    numerically, so max() keeps the real update."""
    cur = words[slot, word]
    return words.at[slot, word].max(cur | mask)


# -- fused count over the mesh ----------------------------------------------

def _leaf_container_indices(keys, idxs):
    """Per-leaf container locations for a shard's pool.

    keys: (S, cap) sorted pool keys; idxs: (L,) leaf dense-row ids.
    Returns idx (L, S, 16) int32 clipped container positions and
    hit (L, S, 16) int32 presence mask — the searchsorted half of
    gather_row (ops/pool.py), hoisted out so a kernel can stream the
    containers directly."""
    num_leaves = idxs.shape[0]
    targets = (idxs[:, None] * ROW_SPAN
               + jnp.arange(ROW_SPAN, dtype=jnp.int32)[None, :])  # (L, 16)
    flat = targets.reshape(-1)

    def one(k):
        i = jnp.searchsorted(k, flat).astype(jnp.int32)
        i = jnp.clip(i, 0, k.shape[0] - 1)
        return i, (k[i] == flat).astype(jnp.int32)

    idx, hit = jax.vmap(one)(keys)           # (S, L*16) each
    shape = (keys.shape[0], num_leaves, ROW_SPAN)
    return (idx.reshape(shape).transpose(1, 0, 2),
            hit.reshape(shape).transpose(1, 0, 2))


def compile_mesh_count(mesh: Mesh, tree_shape, num_leaves: int,
                       backend: Optional[str] = None):
    """Jit a Count over a bitmap-op tree for a mesh-sharded index.

    Returns fn(sharded_index, leaf_dense_ids (num_leaves,) int32) -> int32
    replicated global count, psum'd over the slice axis (ICI).

    backend: "xla" = vmapped gather + fused XLA combine, "pallas" =
    fused in-kernel container streaming (ops/kernels.tree_count_pallas),
    "pallas_interpret" = the Pallas kernel in interpret mode
    (differential tests on CPU). None: the PILOSA_TPU_COUNT_BACKEND
    env var if set, else "xla". "auto" (what config.apply_mesh_env
    installs as the serving default) resolves through the measured
    startup calibration (ops/calibrate) the same way the serving
    layer's dispatch does — xla while a probe is still pending.
    """
    sig = json.dumps(_tree_signature(tree_shape))
    tree = json.loads(sig)
    if backend is None:
        import os
        backend = os.environ.get("PILOSA_TPU_COUNT_BACKEND", "xla")
    if backend == "auto":
        from ..ops.calibrate import resolve_backend
        backend = "pallas" if resolve_backend(wait=False) == "pallas" \
            else "xla"
    if backend not in ("xla", "pallas", "pallas_interpret"):
        raise ValueError(f"unknown count backend: {backend!r} "
                         "(want xla, pallas, or pallas_interpret)")

    if backend == "xla":
        one_slice = partial(_count_one_slice, tree, num_leaves)

        def per_shard(keys, words, idxs):
            counts = jax.vmap(one_slice, in_axes=(0, 0, None))(
                keys, words, idxs)
            return lax.psum(counts.sum(), SLICE_AXIS)
    else:
        from ..ops.kernels import tree_count_pallas, tree_count_pallas_coarse
        interpret = backend == "pallas_interpret"

        def coarse_starts(keys, idxs):
            """In-program coarse eligibility (the traced twin of
            coarse_row_starts): per (leaf, slice), the signed row-run
            index when the slice holds the row as one full 16-aligned
            run (or none of it), plus an eligibility flag. Any
            ineligible (partial/unaligned) pair falls the whole call
            back to the general slab kernel via lax.cond."""
            cap = keys.shape[1]

            def one(keys_s, dense_id):
                lo = dense_id * ROW_SPAN
                pos = jnp.searchsorted(keys_s, lo).astype(jnp.int32)
                pos_c = jnp.clip(pos, 0, cap - ROW_SPAN)
                run = lax.dynamic_slice(keys_s, (pos_c,), (ROW_SPAN,))
                present = jnp.any((keys_s >= lo) & (keys_s < lo + ROW_SPAN))
                full = (jnp.all(run == lo + jnp.arange(ROW_SPAN,
                                                       dtype=keys_s.dtype))
                        & (pos_c % ROW_SPAN == 0) & (pos_c == pos))
                ok = jnp.logical_or(~present, full)
                start = jnp.where(present & full, pos_c // ROW_SPAN,
                                  jnp.int32(-1))
                return start, ok

            starts, ok = jax.vmap(
                lambda d: jax.vmap(lambda k: one(k, d))(keys))(idxs)
            return starts, jnp.all(ok)  # (L, S), scalar

        def per_shard(keys, words, idxs):
            idx, hit = _leaf_container_indices(keys, idxs)
            if words.shape[1] % ROW_SPAN != 0:
                # Pre-padding staged image: statically ineligible for
                # the coarse kernel — the check must be PYTHON-level,
                # because lax.cond traces both branches and the coarse
                # kernel's reshape would fail on the unpadded cap.
                count = tree_count_pallas(words, idx, hit, tree,
                                          interpret=interpret)
            else:
                starts, eligible = coarse_starts(keys, idxs)
                count = lax.cond(
                    eligible,
                    lambda: tree_count_pallas_coarse(
                        words, starts, tree, interpret=interpret),
                    lambda: tree_count_pallas(words, idx, hit, tree,
                                              interpret=interpret))
            return lax.psum(count, SLICE_AXIS)

    fn = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(SLICE_AXIS), P(SLICE_AXIS), P()),
        out_specs=P(),
        # pallas_call can't annotate how its output varies over mesh
        # axes, which the VMA checker requires (backend != "xla").
        check_vma=(backend == "xla"),
    )

    @jax.jit
    def run(index: ShardedIndex, leaf_ids):
        return fn(index.keys, index.words, leaf_ids)

    return run


# -- exact TopN over the mesh ------------------------------------------------

def compile_mesh_topn(mesh: Mesh, num_rows: int, k: int):
    """Jit an EXACT TopN: global per-row popcounts + replicated top_k.

    Returns fn(sharded_index) -> (counts (k,) int32, dense_row_ids (k,)).
    A k beyond the row count clamps (TopN(n) with n > rows returns
    every row, executor.go:273-310 semantics).
    """
    k = min(k, num_rows)
    one_slice = partial(_row_counts_one_slice, num_rows)

    def per_shard(keys, words):
        local = jax.vmap(one_slice)(keys, words).sum(axis=0)
        total = lax.psum(local, SLICE_AXIS)
        vals, ids = lax.top_k(total, k)
        return vals, ids

    fn = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(SLICE_AXIS), P(SLICE_AXIS)),
        out_specs=(P(), P()),
    )

    @jax.jit
    def run(index: ShardedIndex):
        return fn(index.keys, index.words)

    return run


# -- device-side write application -------------------------------------------

def plan_writes(keys: np.ndarray, row_ids: np.ndarray,
                slice_writes: List[Tuple[np.ndarray, np.ndarray]],
                batch: int):
    """Host-side write planning: (row, col) batches per slice →
    (slot, word, mask) scatter plans with OR-combined duplicates.

    The device applies bits only into containers already present in the
    pool (SURVEY.md §7 "mutation on device" hard part: host buffers
    writes, device applies them as one scatter per step; container
    allocation stays a host responsibility). Unknown rows/containers are
    dropped — callers must ensure containers exist (import path does).
    Returns (slot (S,B), word (S,B), mask (S,B)) int32/uint32, padded
    with no-op (slot=0, mask=0) entries. Raises ValueError when a
    slice's distinct scatter targets exceed `batch` — a partial write
    must never be applied silently.
    """
    s = keys.shape[0]
    slot = np.zeros((s, batch), dtype=np.int32)
    word = np.zeros((s, batch), dtype=np.int32)
    mask = np.zeros((s, batch), dtype=np.uint32)
    for si, (rows, cols) in enumerate(slice_writes):
        if rows is None or len(rows) == 0 or len(row_ids) == 0:
            continue
        rows = np.asarray(rows, dtype=np.uint64)
        cols = np.asarray(cols, dtype=np.uint64) % np.uint64(SLICE_WIDTH)
        dense = np.searchsorted(row_ids, rows)
        ok = (dense < len(row_ids)) & (row_ids[np.minimum(dense, len(row_ids) - 1)] == rows)
        pos = rows * np.uint64(SLICE_WIDTH) + cols
        key = (dense * ROW_SPAN + ((pos >> np.uint64(16)) & np.uint64(15)).astype(np.int64)).astype(np.int32)
        sl = np.searchsorted(keys[si], key)
        ok &= (sl < keys.shape[1]) & (keys[si][np.minimum(sl, keys.shape[1] - 1)] == key)
        wd = ((pos & np.uint64(0xFFFF)) >> np.uint64(5)).astype(np.int32)
        mk = (np.uint32(1) << (pos & np.uint64(31)).astype(np.uint32))
        sl, wd, mk = sl[ok], wd[ok], mk[ok]
        # OR-combine duplicates so the device scatter has unique targets.
        flat = sl.astype(np.int64) * CONTAINER_WORDS + wd
        order = np.argsort(flat, kind="stable")
        flat, sl, wd, mk = flat[order], sl[order], wd[order], mk[order]
        uniq, start = np.unique(flat, return_index=True)
        combined = np.bitwise_or.reduceat(mk, start) if len(mk) else mk
        if len(uniq) > batch:
            raise ValueError(
                f"slice {si}: {len(uniq)} scatter targets exceed write "
                f"batch {batch}; split the write batch")
        n = len(uniq)
        slot[si, :n] = sl[start][:n]
        word[si, :n] = wd[start][:n]
        mask[si, :n] = combined[:n]
    return slot, word, mask


def compile_mesh_apply_writes(mesh: Mesh):
    """Jit the per-step scatter-OR of planned writes into the sharded
    pools. Write plans have unique (slot, word) targets per slice
    (plan_writes), so gather-OR-scatter is exact."""

    def per_shard(keys, words, slot, word, mask):
        return keys, jax.vmap(_apply_writes_one_slice)(words, slot, word, mask)

    fn = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(SLICE_AXIS),) * 5,
        out_specs=(P(SLICE_AXIS), P(SLICE_AXIS)),
    )

    @jax.jit
    def run(index: ShardedIndex, slot, word, mask):
        keys, words = fn(index.keys, index.words, slot, word, mask)
        return ShardedIndex(keys=keys, words=words)

    return run


def compile_mesh_step(mesh: Mesh, tree_shape, num_leaves: int,
                      num_rows: int, k: int):
    """The full per-step pipeline as ONE jitted shard_map: apply a
    planned write batch to the sharded pools, evaluate a fused count
    query, and compute the exact global TopN — write scatter, query
    dataflow, and both ICI reductions in a single XLA program. This is
    the multi-chip "training step" the driver dry-runs
    (__graft_entry__.dryrun_multichip).
    """
    sig = json.dumps(_tree_signature(tree_shape))
    tree = json.loads(sig)
    count_one = partial(_count_one_slice, tree, num_leaves)
    rows_one = partial(_row_counts_one_slice, num_rows)

    def per_shard(keys, words, slot, word, mask, leaf_ids):
        # 1. writes
        words = jax.vmap(_apply_writes_one_slice)(words, slot, word, mask)

        # 2. fused count query over the updated pools
        count = lax.psum(
            jax.vmap(count_one, in_axes=(0, 0, None))(keys, words, leaf_ids).sum(),
            SLICE_AXIS)

        # 3. exact TopN over all rows
        totals = lax.psum(jax.vmap(rows_one)(keys, words).sum(axis=0), SLICE_AXIS)
        top_vals, top_ids = lax.top_k(totals, k)
        return keys, words, count, top_vals, top_ids

    fn = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(SLICE_AXIS),) * 5 + (P(),),
        out_specs=(P(SLICE_AXIS), P(SLICE_AXIS), P(), P(), P()),
    )

    @jax.jit
    def run(index: ShardedIndex, slot, word, mask, leaf_ids):
        keys, words, count, top_vals, top_ids = fn(
            index.keys, index.words, slot, word, mask, leaf_ids)
        return ShardedIndex(keys=keys, words=words), count, top_vals, top_ids

    return run


# -- serving-path kernels ----------------------------------------------------
#
# The compile_serve_* family is what the query Executor calls when an
# HTTP query reaches a node (the TPU answer to the reference's
# goroutine-per-slice local fan-out, executor.go:1200-1236): one
# shard_map'd computation evaluates every locally-owned slice, with a
# per-slice ownership mask so the same staged index serves any slice
# subset, and psum reductions ride ICI. Counts come back as two int32
# limbs (lo16/hi) combined host-side — a dense multi-B-column index
# overflows a single int32 accumulator (the JAX default config has no
# device int64), so the device never sums raw counts across slices.


def combine_count(limbs) -> int:
    """Host-side combine of a (2,) [lo, hi] int32 limb array.

    The limbs travel as ONE device array, not two scalars: each scalar
    fetch through a remote-TPU relay pays a full readback round trip
    (~70 ms observed), so the device packs both limbs before the host
    reads anything."""
    limbs = np.asarray(limbs)
    return (int(limbs[1]) << 16) + int(limbs[0])


def resolve_row_indices(keys_host: np.ndarray, dense_id: int):
    """Host-side row → container-location resolution for the serving
    count path.

    keys_host: (S, cap) sorted int32 pool keys (INVALID_KEY padded).
    Returns (idx (S, 16) int32 WITHIN-SLICE container indices in
    [0, cap) and hit (S, 16) uint32). Indices are within-slice — not
    flat — because inside shard_map each shard only holds its local
    slice block; the kernel adds its own local base (a global flat
    index would only be right on a 1-device mesh).

    This work lives on the HOST deliberately: an in-program vmapped
    searchsorted measured ~2.2 ms/query on a 960-slice pool on real TPU
    hardware vs ~0.1 ms of vectorized numpy here, and the result only
    changes when the pool's key layout changes (restage), so the
    serving layer caches the device copies per (view, row). One
    searchsorted over slice-offset int64 keys resolves every slice at
    once; a clipped miss lands on an arbitrary in-range container, but
    hit=0 multiplies that gather to zero.
    """
    s, cap = keys_host.shape
    off = (np.arange(s, dtype=np.int64) << 33)[:, None]
    k64 = (keys_host.astype(np.int64) + off).reshape(-1)
    t = dense_id * ROW_SPAN + np.arange(ROW_SPAN, dtype=np.int64)
    t64 = (t[None, :] + off).reshape(-1)
    i = np.searchsorted(k64, t64)
    i = np.minimum(i, s * cap - 1)
    hit = (k64[i] == t64).astype(np.uint32)
    within = np.clip(i.reshape(s, ROW_SPAN)
                     - (np.arange(s, dtype=np.int64) * cap)[:, None],
                     0, cap - 1)
    return within.astype(np.int32), hit.reshape(s, ROW_SPAN)


def _gather_leaf_blocks(words_t, idx_t, hit_t, i):
    """One leaf's (S_local*16, CONTAINER_WORDS) gathered blocks for the
    serving kernels: a flat gather from the leaf's own pool using the
    host-resolved within-slice indices, zeroed where the container is
    absent (hit == 0). The ONE implementation every compile_serve_*
    kernel folds its tree over — the gather indexing and absent-row
    semantics cannot drift between the count, batch, src, and tanimoto
    programs."""
    w = words_t[i]
    cap = w.shape[1]
    wflat = w.reshape(w.shape[0] * cap, w.shape[2])
    base = (jnp.arange(w.shape[0], dtype=jnp.int32) * cap)[:, None]
    blk = wflat[(idx_t[i] + base).reshape(-1)]
    return blk * hit_t[i].reshape(-1)[:, None]


def coarse_row_starts(keys_host: np.ndarray, dense_id: int):
    """Host-side COARSE eligibility check for one leaf row: when every
    slice holds the row's 16 containers as one contiguous, 16-aligned
    run (or holds none of them), the serving kernels can gather the row
    as ONE (16*CONTAINER_WORDS)-word run per slice instead of 16
    separate container gathers — measured 125 -> 165 GB/s effective
    bandwidth on the 960-slice headline pool (tools/profile_batch.py),
    the difference between 9.2x and 12x on the recorded throughput.

    This is the data-adaptive dispatch the reference does by container
    TYPE (roaring.go:1270-1351 array/bitmap kernel table) done instead
    by container LAYOUT. Dense popular rows stage contiguously (stagers
    sort keys, and build_sharded_index pads capacity to a ROW_SPAN
    multiple, so fully-dense rows land aligned); sparse or partial rows
    fall back to the general gather path (resolve_row_indices).

    Returns (starts (S,) int32 row-run indices [pos/16], valid (S,)
    uint32 presence flags) or None when any slice is partial/unaligned.
    """
    s, cap = keys_host.shape
    if cap % ROW_SPAN != 0:
        return None  # pre-padding staged image (build_sharded_index
        #              now always pads; old images fall back)
    lo = np.int64(dense_id) * ROW_SPAN
    # Position of the row's first container in each slice's sorted
    # keys: one searchsorted over slice-offset int64 keys (same scheme
    # as resolve_row_indices).
    off = np.arange(s, dtype=np.int64) * (np.int64(1) << 33)
    k64 = (keys_host.astype(np.int64) + off[:, None]).reshape(-1)
    pos = np.searchsorted(k64, lo + off) - np.arange(s, dtype=np.int64) * cap
    pos = np.clip(pos, 0, cap - 1)
    present = keys_host[np.arange(s), pos] == lo
    if not present.any():
        return None  # staged nowhere: the general path answers zero
        #              via hit=0 without a special case here
    ps = pos[present]
    if ((ps % ROW_SPAN) != 0).any():
        return None
    rows = ps // ROW_SPAN
    run = keys_host.reshape(s, cap // ROW_SPAN, ROW_SPAN)[
        np.flatnonzero(present), rows]
    want = lo + np.arange(ROW_SPAN, dtype=np.int64)
    if not (run == want[None, :]).all():
        return None
    starts = np.zeros(s, dtype=np.int32)
    starts[present] = rows.astype(np.int32)
    return starts, present.astype(np.uint32)


def _gather_leaf_rows(words_t, start_t, valid_t, i):
    """One coarse leaf's (S_local, 16*CONTAINER_WORDS) row runs: a
    whole-row gather from the pool viewed as (S, cap/16, 16*W), zeroed
    where the slice holds no part of the row (valid == 0). The coarse
    counterpart of _gather_leaf_blocks."""
    w = words_t[i]
    s_l, cap = w.shape[0], w.shape[1]
    wr = w.reshape(s_l, cap // ROW_SPAN, ROW_SPAN * w.shape[2])

    def one(wrow, st):
        return wrow[st]

    g = jax.vmap(one)(wr, start_t[i])
    return g * valid_t[i][:, None]


def _limb_psum(per_bs):
    """(B, S_l) uint32 per-(query, slice) counts -> (2, B) [lo, hi]
    16-bit limb columns psum'd over the slice axis — the shared
    epilogue of every serving count program (a per-slice count is
    <= 2^20, so the 16-bit split keeps the int32 psum exact at any
    slice fan-out)."""
    lo = lax.psum(
        (per_bs & jnp.uint32(0xFFFF)).astype(jnp.int32).sum(axis=1),
        SLICE_AXIS)
    hi = lax.psum((per_bs >> 16).astype(jnp.int32).sum(axis=1),
                  SLICE_AXIS)
    return jnp.stack([lo, hi])


def compile_serve_count_coarse(mesh: Mesh, tree_shape, num_leaves: int,
                               batch: int = 1):
    """Jit a masked Count (batch >= 1) where EVERY leaf is a coarse
    whole-row run (coarse_row_starts eligible). Signature mirrors
    compile_serve_count_batch with (starts, valid) per leaf instead of
    (idx, hit):
      fn(words_t (L,), start_flat (batch*L,) of (S,) int32,
         valid_flat (batch*L,) of (S,) uint32, mask (S,))
      -> (2, batch) [lo, hi] limb columns ((2,) squeezed is NOT done —
      batch=1 still returns (2, 1); callers index [:, 0]).
    """
    sig = json.dumps(_tree_signature(tree_shape))
    tree = json.loads(sig)
    from ..ops.bitops import fold_tree

    def per_shard(words_t, start_flat, valid_flat, mask):
        s_l = words_t[0].shape[0]

        def one(b):
            def leaf(i):
                return _gather_leaf_rows(
                    words_t, start_flat[b * num_leaves:(b + 1) * num_leaves],
                    valid_flat[b * num_leaves:(b + 1) * num_leaves], i)

            pc = lax.population_count(fold_tree(tree, leaf))  # (S_l, 16W)
            return pc.sum(axis=1, dtype=jnp.uint32)

        per_slice = jnp.stack([one(b) for b in range(batch)])  # (B, S_l)
        per_slice = jnp.where(mask[None, :] != 0, per_slice, jnp.uint32(0))
        lo = lax.psum(
            (per_slice & jnp.uint32(0xFFFF)).astype(jnp.int32).sum(axis=1),
            SLICE_AXIS)
        hi = lax.psum((per_slice >> 16).astype(jnp.int32).sum(axis=1),
                      SLICE_AXIS)
        return jnp.stack([lo, hi])

    fn = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=((P(SLICE_AXIS),) * num_leaves,
                  (P(SLICE_AXIS),) * (batch * num_leaves),
                  (P(SLICE_AXIS),) * (batch * num_leaves),
                  P(SLICE_AXIS)),
        out_specs=P(),
    )

    @jax.jit
    def run(words_t, start_flat, valid_flat, mask):
        return fn(words_t, start_flat, valid_flat, mask)

    return run


def compile_serve_count_coarse_pallas(mesh: Mesh, tree_shape,
                                      num_leaves: int,
                                      interpret: bool = False):
    """Pallas twin of compile_serve_count_coarse (batch=1): identical
    call contract — fn(words_t (L,), start_flat (L,) of (S,) int32,
    valid_flat (L,) of (S,) uint32, mask (S,)) -> (2, 1) limb column —
    but the fold+popcount runs as ONE pallas_call per shard streaming
    each leaf's whole 128 KB row run HBM->VMEM exactly once (VERDICT
    r4 #2: the general Pallas kernel's (L, S, 16) SMEM tables forced
    slab launches that each paid the dispatch floor; the coarse form's
    per-(leaf, slice) state is ONE signed int, so any S fits one
    launch). The XLA gather path materializes each gathered row copy
    back to HBM before combining — ~3x the memory traffic of this
    kernel's read-once stream. Off by default
    (PILOSA_TPU_COUNT_BACKEND=pallas opts in): Pallas cannot compile
    through the single-chip relay this rig benches on; differential
    coverage runs in interpret mode on the CPU mesh."""
    from ..ops.kernels import coarse_count_per_slice

    sig = json.dumps(_tree_signature(tree_shape))
    tree = json.loads(sig)

    def per_shard(words_t, start_flat, valid_flat, mask):
        # Fold validity AND slice ownership into the sign: the kernel
        # masks blocks by `start >= 0` alone.
        starts = jnp.stack([
            jnp.where((valid_flat[i] != 0) & (mask != 0),
                      start_flat[i], jnp.int32(-1))
            for i in range(num_leaves)])
        per_slice = coarse_count_per_slice(
            tuple(words_t), starts, tree,
            interpret=interpret)[0].astype(jnp.uint32)
        lo = lax.psum(
            (per_slice & jnp.uint32(0xFFFF)).astype(jnp.int32).sum(),
            SLICE_AXIS)
        hi = lax.psum((per_slice >> 16).astype(jnp.int32).sum(),
                      SLICE_AXIS)
        return jnp.stack([lo, hi]).reshape(2, 1)

    fn = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=((P(SLICE_AXIS),) * num_leaves,
                  (P(SLICE_AXIS),) * num_leaves,
                  (P(SLICE_AXIS),) * num_leaves,
                  P(SLICE_AXIS)),
        out_specs=P(),
        # pallas_call can't annotate how its output varies over mesh
        # axes, which the VMA checker requires.
        check_vma=False,
    )

    @jax.jit
    def run(words_t, start_flat, valid_flat, mask):
        return fn(words_t, start_flat, valid_flat, mask)

    return run


def compile_serve_count_coarse_pallas_uniform(mesh: Mesh, tree_shape,
                                              num_leaves: int,
                                              batch: int = 1,
                                              interpret: bool = False):
    """Uniform-layout Pallas coarse count: fn(words_t (L,), starts
    (B*L,) int32 scalar row-run per slot, mask (S,)) -> (2, B) limb
    columns. Selected when the serving layer detects (host-side, from
    the staged keys) that every leaf sits at ONE row-run index across
    all slices — true for any densely staged pool — which lets the
    kernel fetch multiple consecutive slices per grid step and reach
    the chip's streaming ceiling (ops.kernels.coarse_count_uniform;
    257 -> 360 GB/s measured, PROBE_R5_bw.json). Slice-ownership masks
    apply AFTER the kernel: the per-slice counts are multiplied by the
    mask before the limb psum, so validity never needs a per-slice
    starts table."""
    from ..ops.kernels import coarse_count_uniform, coarse_count_uniform_batch

    sig = json.dumps(_tree_signature(tree_shape))
    tree = json.loads(sig)

    def per_shard(words_t, starts, mask):
        own = (mask != 0).astype(jnp.int32)
        if batch == 1:
            per_slice = coarse_count_uniform(
                tuple(words_t), starts, tree,
                interpret=interpret)[0]
            per_bs = (per_slice * own)[None, :].astype(jnp.uint32)
        else:
            per_bs = coarse_count_uniform_batch(
                tuple(words_t), starts, tree,
                interpret=interpret)
            per_bs = (per_bs * own[None, :]).astype(jnp.uint32)
        return _limb_psum(per_bs)

    fn = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=((P(SLICE_AXIS),) * num_leaves,
                  P(),  # starts are global scalars, replicated
                  P(SLICE_AXIS)),
        out_specs=P(),
        # pallas_call can't annotate how its output varies over mesh
        # axes, which the VMA checker requires.
        check_vma=False,
    )

    @jax.jit
    def run(words_t, starts, mask):
        return fn(tuple(words_t), starts, mask)

    return run


def compile_serve_count_batch_shared(mesh: Mesh, tree_shape,
                                     leaf_map: Tuple[Tuple[int, ...], ...],
                                     num_unique: int):
    """Jit a SHARED-READ coarse batch count: B queries of one tree
    shape over U unique coarse leaves, reading each unique leaf's data
    ONCE per slice instead of once per query.

    The plain batch program (compile_serve_count_coarse) makes every
    query gather its own leaves: a batch of B two-leaf queries over U
    unique rows moves B*2 row-reads of HBM traffic. Here a lax.scan
    walks the local slices; each step gathers the U unique row-runs for
    that slice (U * 128 KB — VMEM-resident while the step computes) and
    evaluates ALL B query folds from those blocks, so traffic scales
    with UNIQUE leaves: the 28-distinct-pair headline reads the 8-row
    pool once (~1 GB) instead of 28 pairs x 2 rows (~7 GB). This is the
    device analog of the reference's per-fragment row cache serving
    many queries from one materialized row (fragment.go:332-367 +
    BitmapCache) — except the "cache" is one scan step's VMEM block.

    leaf_map is STATIC: leaf_map[b] gives, per leaf position of the
    tree, the unique-leaf index it reads. The compile cache key must
    include it (serve.MeshManager memoizes by (sig, leaf_map)).

    Returns fn(words_t (U,), start_t (U,) of (S,) int32 row-run
    indices, valid_t (U,) of (S,) uint32, mask (S,) int32)
    -> (2, B) [lo, hi] limb columns (same contract as
    compile_serve_count_coarse).
    """
    sig = json.dumps(_tree_signature(tree_shape))
    tree = json.loads(sig)
    from ..ops.bitops import fold_tree

    batch = len(leaf_map)

    def per_shard(words_t, start_t, valid_t, mask):
        s_l = words_t[0].shape[0]
        w = ROW_SPAN * words_t[0].shape[2]
        wr_t = tuple(
            wt.reshape(s_l, wt.shape[1] // ROW_SPAN, w) for wt in words_t)
        start_st = jnp.stack(start_t)            # (U, S_l)
        valid_st = jnp.stack(valid_t)            # (U, S_l)

        def step(acc, s):
            # Gather each UNIQUE leaf's whole-row run for slice s —
            # read once, used by every query below. The barrier is the
            # load-bearing part: without it XLA is free to fuse (i.e.
            # DUPLICATE) each cheap dynamic-slice gather into every
            # consuming fold, re-reading HBM per query and silently
            # degenerating this program to the plain batch's traffic —
            # r3 measured the two at identical wall time, which is
            # exactly that failure. The barrier forces the U blocks to
            # materialize once (U * 128 KB, VMEM-resident) before the
            # B folds consume them.
            blocks = list(lax.optimization_barrier(tuple(
                wr_t[u][s, start_st[u, s]]
                * valid_st[u, s].astype(jnp.uint32)
                for u in range(num_unique))))

            live = (mask[s] != 0).astype(jnp.uint32)
            outs = []
            for b in range(batch):
                blk = fold_tree(tree, lambda i: blocks[leaf_map[b][i]])
                pc = lax.population_count(blk).sum(dtype=jnp.uint32) * live
                outs.append(pc)
            per_slice = jnp.stack(outs)          # (B,) uint32
            lo = (per_slice & jnp.uint32(0xFFFF)).astype(jnp.int32)
            hi = (per_slice >> 16).astype(jnp.int32)
            return (acc[0] + lo, acc[1] + hi), None

        # pcast to varying: the scan carry accumulates shard-local
        # values, so its init must be marked varying over the mesh
        # axis for the VMA checker.
        init = (_pcast(jnp.zeros(batch, jnp.int32), (SLICE_AXIS,),
                       to="varying"),
                _pcast(jnp.zeros(batch, jnp.int32), (SLICE_AXIS,),
                       to="varying"))
        (lo, hi), _ = lax.scan(step, init,
                               jnp.arange(s_l, dtype=jnp.int32))
        return jnp.stack([lax.psum(lo, SLICE_AXIS),
                          lax.psum(hi, SLICE_AXIS)])

    fn = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=((P(SLICE_AXIS),) * num_unique,
                  (P(SLICE_AXIS),) * num_unique,
                  (P(SLICE_AXIS),) * num_unique,
                  P(SLICE_AXIS)),
        out_specs=P(),
    )

    @jax.jit
    def run(words_t, start_t, valid_t, mask):
        return fn(words_t, start_t, valid_t, mask)

    return run


def compile_serve_count_coarse_pallas_batch(mesh: Mesh, tree_shape,
                                            num_leaves: int, batch: int,
                                            interpret: bool = False):
    """Pallas twin of compile_serve_count_coarse for batch > 1 — the
    plain (no leaf sharing assumed) herd-group program. Same call
    contract: fn(words_t (L,), start_flat (B*L,) of (S,) int32,
    valid_flat (B*L,) of (S,) uint32, mask (S,)) -> (2, B).

    One compile serves every ad-hoc width-B herd of this tree shape
    (the shared machinery's per-composition maps would recompile per
    herd): the (b, s) grid picks each slot's row-run from the
    scalar-prefetched starts table, so which rows the queries name is
    DATA, not program. Sharing saves no reads here, but the grid
    kernel still skips the XLA batch program's gathered HBM
    intermediates and pipelines per-slice DMA under the B folds, which
    is where the plain XLA batch spends its time at herd widths."""
    from ..ops.kernels import coarse_count_identity_batch

    sig = json.dumps(_tree_signature(tree_shape))
    tree = json.loads(sig)
    slots = batch * num_leaves

    def per_shard(words_t, start_flat, valid_flat, mask):
        starts = jnp.stack([
            jnp.where((valid_flat[k] != 0) & (mask != 0),
                      start_flat[k], jnp.int32(-1))
            for k in range(slots)])
        per_bs = coarse_count_identity_batch(
            tuple(words_t), starts, tree,
            interpret=interpret).astype(jnp.uint32)      # (B, S_l)
        return _limb_psum(per_bs)

    fn = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=((P(SLICE_AXIS),) * num_leaves,
                  (P(SLICE_AXIS),) * slots,
                  (P(SLICE_AXIS),) * slots,
                  P(SLICE_AXIS)),
        out_specs=P(),
        # pallas_call can't annotate how its output varies over mesh
        # axes, which the VMA checker requires.
        check_vma=False,
    )

    @jax.jit
    def run(words_t, start_flat, valid_flat, mask):
        return fn(tuple(words_t), tuple(start_flat), tuple(valid_flat),
                  mask)

    return run


def compile_serve_count_batch_shared_pallas(mesh: Mesh, tree_shape,
                                            leaf_map, num_unique: int,
                                            interpret: bool = False):
    """Pallas twin of compile_serve_count_batch_shared: identical call
    contract — fn(words_t (U,), start_t (U,) of (S,) int32, valid_t
    (U,) of (S,) uint32, mask (S,)) -> (2, B) limb columns — but the
    shared-read fold runs as ONE pallas_call per shard
    (ops.kernels.coarse_count_batch_per_slice). The XLA program's
    lax.scan walks slices SEQUENTIALLY, each step doing microseconds
    of compute behind an optimization_barrier; on the r5 chip that
    latency-bound loop measured SLOWER than the plain per-query batch
    (353 vs 569 QPS) even though it moves 7x less HBM traffic. The
    pallas grid keeps the traffic win and pipelines the per-slice DMA
    under compute. Selected by PILOSA_TPU_COUNT_BACKEND=pallas
    (serve.MeshManager._shared_* machinery; key carries the backend)."""
    from ..ops.kernels import coarse_count_batch_per_slice

    sig = json.dumps(_tree_signature(tree_shape))
    tree = json.loads(sig)
    leaf_map = tuple(tuple(m) for m in leaf_map)

    def per_shard(words_t, start_t, valid_t, mask):
        starts = jnp.stack([
            jnp.where((valid_t[u] != 0) & (mask != 0),
                      start_t[u], jnp.int32(-1))
            for u in range(num_unique)])
        per_bs = coarse_count_batch_per_slice(
            tuple(words_t), starts, tree, leaf_map,
            interpret=interpret).astype(jnp.uint32)      # (B, S_l)
        return _limb_psum(per_bs)

    fn = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=((P(SLICE_AXIS),) * num_unique,
                  (P(SLICE_AXIS),) * num_unique,
                  (P(SLICE_AXIS),) * num_unique,
                  P(SLICE_AXIS)),
        out_specs=P(),
        # pallas_call can't annotate how its output varies over mesh
        # axes, which the VMA checker requires.
        check_vma=False,
    )

    @jax.jit
    def run(words_t, start_t, valid_t, mask):
        return fn(words_t, start_t, valid_t, mask)

    return run


def compile_serve_count_batch_shared_pallas_uniform(
        mesh: Mesh, tree_shape, leaf_map, num_unique: int,
        interpret: bool = False):
    """Uniform-layout shared-read batch: fn(words_t (U,), starts (U,)
    int32 scalar row-run per unique, mask (S,)) -> (2, B). Combines
    the shared program's unique-leaf traffic win with the uniform
    kernel's multi-slice DMA amortization
    (ops.kernels.coarse_count_shared_uniform); the serving layer
    selects it when _shared_plan sees every unique leaf staged at one
    row-run index across all slices."""
    from ..ops.kernels import coarse_count_shared_uniform

    sig = json.dumps(_tree_signature(tree_shape))
    tree = json.loads(sig)
    leaf_map = tuple(tuple(m) for m in leaf_map)

    def per_shard(words_t, starts, mask):
        per_bs = coarse_count_shared_uniform(
            tuple(words_t), starts, tree, leaf_map,
            interpret=interpret)
        per_bs = (per_bs * (mask != 0).astype(jnp.int32)[None, :]
                  ).astype(jnp.uint32)
        return _limb_psum(per_bs)

    fn = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=((P(SLICE_AXIS),) * num_unique,
                  P(),  # starts are global scalars, replicated
                  P(SLICE_AXIS)),
        out_specs=P(),
        # pallas_call can't annotate how its output varies over mesh
        # axes, which the VMA checker requires.
        check_vma=False,
    )

    @jax.jit
    def run(words_t, starts, mask):
        return fn(tuple(words_t), starts, mask)

    return run


def _segment_rows(pc, dense, num_rows):
    """vmap'd per-slice segment-sum of per-container counts into dense
    rows: (S, cap) pc + (S, cap) dense ids -> (S, num_rows)."""

    def one(pc_row, dense_row):
        return jax.ops.segment_sum(pc_row, dense_row,
                                   num_segments=num_rows + 1)[:num_rows]

    return jax.vmap(one)(pc, dense)


def _src_block_per_container(keys, src_blk, s_l):
    """Align an evaluated src tree's (S*16, W) blocks with a pool's
    containers: each container ANDs against the src block of its own
    sub-key (key mod 16). Returns (src_per_container (S, cap, W),
    valid (S, cap) presence mask). Shared by the src and tanimoto
    row-count kernels so the sub-key gather can't diverge."""
    src_blk3 = src_blk.reshape(s_l, ROW_SPAN, CONTAINER_WORDS)
    valid = keys != INVALID_KEY
    sub = jnp.where(valid, keys % ROW_SPAN, 0)
    return jnp.take_along_axis(src_blk3, sub[:, :, None], axis=1), valid


def compile_serve_count(mesh: Mesh, tree_shape, num_leaves: int):
    """Jit a masked Count over a bitmap-op tree with PER-LEAF pools and
    HOST-RESOLVED container indices.

    Each leaf is one flat gather from its own view's pool — a served
    tree may span frames and time-quantum views. Returns
      fn(words_t: tuple per leaf of (S, cap_i, 2048) sharded words,
         idx_t:   tuple per leaf of (S, 16) int32 flat gather indices
                  (resolve_row_indices, cached on device by the caller),
         hit_t:   tuple per leaf of (S, 16) uint32 presence masks,
         mask (S,) int32 slice-ownership mask)
      -> (lo, hi) int32 limbs; combine with combine_count.

    Per-slice counts are uint32 (safe to 2^32 bits/slice); the lo-limb
    sum is int32-safe to 32k slices (~34T columns). On real v5e
    hardware this shape measured 2.9 ms for a 960-slice (1B-column)
    Intersect+Count vs 5.1 ms for the in-program-searchsorted variant
    and 13.5 ms for the per-slice vmap it replaces. Returns one (2,)
    [lo, hi] array (see combine_count).
    """
    sig = json.dumps(_tree_signature(tree_shape))
    tree = json.loads(sig)
    from ..ops.bitops import fold_tree

    def per_shard(words_t, idx_t, hit_t, mask):
        s_l = words_t[0].shape[0]

        def leaf(i):
            return _gather_leaf_blocks(words_t, idx_t, hit_t, i)

        pc = lax.population_count(fold_tree(tree, leaf))  # (S*16, 2048)
        per_slice = pc.sum(axis=1, dtype=jnp.uint32).reshape(
            s_l, ROW_SPAN).sum(axis=1, dtype=jnp.uint32)
        per_slice = jnp.where(mask != 0, per_slice, jnp.uint32(0))
        lo = lax.psum((per_slice & jnp.uint32(0xFFFF)).astype(jnp.int32).sum(),
                      SLICE_AXIS)
        hi = lax.psum((per_slice >> 16).astype(jnp.int32).sum(), SLICE_AXIS)
        return jnp.stack([lo, hi])

    fn = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=((P(SLICE_AXIS),) * num_leaves,
                  (P(SLICE_AXIS),) * num_leaves,
                  (P(SLICE_AXIS),) * num_leaves,
                  P(SLICE_AXIS)),
        out_specs=P(),
    )

    @jax.jit
    def run(words_t, idx_t, hit_t, mask):
        return fn(words_t, idx_t, hit_t, mask)

    return run


def compile_serve_count_fused(mesh: Mesh, tree_shape, num_leaves: int):
    """compile_serve_count with HOST-ARG metadata: the whole query is
    ONE dispatch.

    The chained serving path uploads each leaf's gather metadata as its
    own jax.device_put (idx, hit, possibly coarse starts) and the mask
    as another before launching the count program — a distinct
    cold-metadata query pays leaf-count + 2 separate device operations,
    each a full ~2.5 ms round trip through a TPU relay (VERDICT r5:
    "three chained dispatches per query"). Here idx/hit/mask are taken
    as REPLICATED host arrays that ride the one jitted call's argument
    transfer, and each shard slices out its local block in-program, so
    a lone query is exactly one dispatch + one fetch.

    Returns
      fn(words_t: tuple per leaf of (S, cap_i, 2048) sharded words,
         idx_all (L, S, 16) int32, hit_all (L, S, 16) uint32 — stacked
         resolve_row_indices outputs, host numpy is fine,
         mask (S,) int32 host slice-ownership mask)
      -> (2,) [lo, hi] limbs; combine with combine_count.

    The (L, S, 16) metadata is replicated to every device — at 960
    slices that is ~120 KB/leaf, noise against the pool itself — and
    the per-shard dynamic_slice is free relative to the gathers it
    feeds. Compiled programs are cached by the serving layer's
    compiled-plan LRU keyed on (tree shape, fragment widths, backend).
    """
    sig = json.dumps(_tree_signature(tree_shape))
    tree = json.loads(sig)
    from ..ops.bitops import fold_tree

    def per_shard(words_t, idx_all, hit_all, mask):
        s_l = words_t[0].shape[0]
        off = lax.axis_index(SLICE_AXIS) * s_l
        idx_l = lax.dynamic_slice_in_dim(idx_all, off, s_l, axis=1)
        hit_l = lax.dynamic_slice_in_dim(hit_all, off, s_l, axis=1)
        mask_l = lax.dynamic_slice_in_dim(mask, off, s_l, axis=0)

        def leaf(i):
            return _gather_leaf_blocks(words_t, idx_l, hit_l, i)

        pc = lax.population_count(fold_tree(tree, leaf))
        per_slice = pc.sum(axis=1, dtype=jnp.uint32).reshape(
            s_l, ROW_SPAN).sum(axis=1, dtype=jnp.uint32)
        per_slice = jnp.where(mask_l != 0, per_slice, jnp.uint32(0))
        lo = lax.psum((per_slice & jnp.uint32(0xFFFF)).astype(jnp.int32).sum(),
                      SLICE_AXIS)
        hi = lax.psum((per_slice >> 16).astype(jnp.int32).sum(), SLICE_AXIS)
        return jnp.stack([lo, hi])

    fn = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=((P(SLICE_AXIS),) * num_leaves, P(), P(), P()),
        out_specs=P(),
    )

    @jax.jit
    def run(words_t, idx_all, hit_all, mask):
        return fn(words_t, idx_all, hit_all, mask)

    return run


def compile_serve_count_batch(mesh: Mesh, tree_shape, num_leaves: int,
                              batch: int):
    """Batched compile_serve_count: `batch` independent queries of the
    same tree shape evaluate in ONE device program.

    Dispatch and readback dominate small-query latency (measured
    ~1.6 ms/call through the TPU relay; 960-slice Intersect+Count went
    310 QPS single → 583 QPS at batch 16), so the serving layer
    coalesces concurrent same-shape queries (serve.MeshManager batch
    loop) and amortizes the floor. Returns
      fn(words_t (L,), idx_flat (batch*L,), hit_flat (batch*L,),
         mask (S,)) -> (2, batch) [lo, hi] limb columns
    where idx_flat/hit_flat are row-major [b][l] per-leaf (S, 16)
    arrays (resolve_row_indices outputs).
    """
    sig = json.dumps(_tree_signature(tree_shape))
    tree = json.loads(sig)
    from ..ops.bitops import fold_tree

    def per_shard(words_t, idx_flat, hit_flat, mask):
        s_l = words_t[0].shape[0]

        def one(b):
            def leaf(i):
                return _gather_leaf_blocks(
                    words_t, idx_flat[b * num_leaves:(b + 1) * num_leaves],
                    hit_flat[b * num_leaves:(b + 1) * num_leaves], i)

            pc = lax.population_count(fold_tree(tree, leaf))
            return pc.sum(axis=1, dtype=jnp.uint32).reshape(
                s_l, ROW_SPAN).sum(axis=1, dtype=jnp.uint32)

        per_slice = jnp.stack([one(b) for b in range(batch)])  # (B, S_l)
        per_slice = jnp.where(mask[None, :] != 0, per_slice, jnp.uint32(0))
        lo = lax.psum(
            (per_slice & jnp.uint32(0xFFFF)).astype(jnp.int32).sum(axis=1),
            SLICE_AXIS)
        hi = lax.psum((per_slice >> 16).astype(jnp.int32).sum(axis=1),
                      SLICE_AXIS)
        return jnp.stack([lo, hi])

    fn = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=((P(SLICE_AXIS),) * num_leaves,
                  (P(SLICE_AXIS),) * (batch * num_leaves),
                  (P(SLICE_AXIS),) * (batch * num_leaves),
                  P(SLICE_AXIS)),
        out_specs=P(),
    )

    @jax.jit
    def run(words_t, idx_flat, hit_flat, mask):
        return fn(words_t, idx_flat, hit_flat, mask)

    return run


def compile_serve_row_counts_src(mesh: Mesh, tree_shape, num_leaves: int,
                                 num_rows: int):
    """Jit masked per-row SRC-INTERSECTION counts: |row ∩ src| for
    every row of one view, where src is a lowered bitmap-op tree
    (reference TopN src semantics, fragment.go:564-608 — there a
    host loop re-intersecting rows one by one; here ONE fused pass).

    Returns fn(keys (S, cap), words (S, cap, 2048) — the TopN view's
    pool — src_words_t/src_idx_t/src_hit_t (per src leaf, as in
    compile_serve_count), mask (S,)) -> (2, num_rows) limb array.
    Each container ANDs against the src block of its own sub-key
    (key mod 16), then popcounts segment-sum by dense row.
    """
    sig = json.dumps(_tree_signature(tree_shape))
    tree = json.loads(sig)
    from ..ops.bitops import fold_tree

    def per_shard(keys, words, src_words_t, src_idx_t, src_hit_t, mask):
        s_l, cap_l = keys.shape

        def leaf(i):
            return _gather_leaf_blocks(src_words_t, src_idx_t, src_hit_t, i)

        src_blk = fold_tree(tree, leaf)                      # (S*16, W)
        # Per-container src sub-block: gather (S, cap, W) from
        # (S, 16, W) — XLA fuses this into the AND+popcount consumer.
        src_per_container, valid = _src_block_per_container(
            keys, src_blk, s_l)
        pc = lax.population_count(words & src_per_container).sum(
            axis=2, dtype=jnp.int32)                         # (S, cap)
        dense = jnp.where(valid, keys // ROW_SPAN, num_rows)
        pc = jnp.where(valid & (mask[:, None] != 0), pc, 0)

        local = _segment_rows(pc, dense, num_rows)           # (S, R)
        lo = lax.psum((local & 0xFFFF).sum(axis=0), SLICE_AXIS)
        hi = lax.psum((local >> 16).sum(axis=0), SLICE_AXIS)
        return jnp.stack([lo, hi])

    fn = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(SLICE_AXIS), P(SLICE_AXIS),
                  (P(SLICE_AXIS),) * num_leaves,
                  (P(SLICE_AXIS),) * num_leaves,
                  (P(SLICE_AXIS),) * num_leaves,
                  P(SLICE_AXIS)),
        out_specs=P(),
    )

    @jax.jit
    def run(keys, words, src_words_t, src_idx_t, src_hit_t, mask):
        return fn(keys, words, src_words_t, src_idx_t, src_hit_t, mask)

    return run


def compile_serve_row_counts_tanimoto(mesh: Mesh, tree_shape,
                                      num_leaves: int, num_rows: int):
    """Jit ALL THREE tanimoto vectors as ONE program: per-row full
    counts, per-row src-intersection counts, and |src| — the fused form
    of the reference's band evaluation inputs (fragment.go:550-608).

    Round 2 ran these as 3-4 separate collectives with a staged-image
    identity re-check between them (a write landing mid-query could zip
    vectors from different generations). One program removes both the
    extra dispatch floors and the consistency window: every vector
    reads the SAME immutable device arrays.

    Returns fn(keys, words — the TopN view's pool —
    src_words_t/src_idx_t/src_hit_t (per src leaf), mask (S,))
    -> (2, 2*num_rows + 1) limb array laid out
       [:, :num_rows]          full per-row counts
       [:, num_rows:2*num_rows] src-intersection per-row counts
       [:, 2*num_rows]          |src|
    — one array, one relay readback (see combine_count).
    """
    sig = json.dumps(_tree_signature(tree_shape))
    tree = json.loads(sig)
    from ..ops.bitops import fold_tree

    def per_shard(keys, words, src_words_t, src_idx_t, src_hit_t, mask):
        s_l, cap_l = keys.shape

        def leaf(i):
            return _gather_leaf_blocks(src_words_t, src_idx_t, src_hit_t, i)

        src_blk = fold_tree(tree, leaf)                 # (S*16, W)

        # |src|: same limb scheme as compile_serve_count.
        src_pc = lax.population_count(src_blk).sum(
            axis=1, dtype=jnp.uint32).reshape(
            s_l, ROW_SPAN).sum(axis=1, dtype=jnp.uint32)
        src_pc = jnp.where(mask != 0, src_pc, jnp.uint32(0))
        src_lo = (src_pc & jnp.uint32(0xFFFF)).astype(jnp.int32).sum()
        src_hi = (src_pc >> 16).astype(jnp.int32).sum()

        src_per_container, valid = _src_block_per_container(
            keys, src_blk, s_l)
        live = valid & (mask[:, None] != 0)
        inter_pc = jnp.where(live, lax.population_count(
            words & src_per_container).sum(axis=2, dtype=jnp.int32), 0)
        full_pc = jnp.where(live, lax.population_count(words).sum(
            axis=2, dtype=jnp.int32), 0)
        dense = jnp.where(valid, keys // ROW_SPAN, num_rows)

        # (S, 2R): full rows then intersection rows, one psum pair.
        both = jnp.concatenate([_segment_rows(full_pc, dense, num_rows),
                                _segment_rows(inter_pc, dense, num_rows)],
                               axis=1)
        lo = lax.psum((both & 0xFFFF).sum(axis=0), SLICE_AXIS)
        hi = lax.psum((both >> 16).sum(axis=0), SLICE_AXIS)
        lo = jnp.concatenate([lo, lax.psum(src_lo, SLICE_AXIS)[None]])
        hi = jnp.concatenate([hi, lax.psum(src_hi, SLICE_AXIS)[None]])
        return jnp.stack([lo, hi])

    fn = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(SLICE_AXIS), P(SLICE_AXIS),
                  (P(SLICE_AXIS),) * num_leaves,
                  (P(SLICE_AXIS),) * num_leaves,
                  (P(SLICE_AXIS),) * num_leaves,
                  P(SLICE_AXIS)),
        out_specs=P(),
    )

    @jax.jit
    def run(keys, words, src_words_t, src_idx_t, src_hit_t, mask):
        return fn(keys, words, src_words_t, src_idx_t, src_hit_t, mask)

    return run


def compile_serve_row_counts(mesh: Mesh, num_rows: int):
    """Jit masked global per-row counts for one sharded view.

    Returns fn(index: ShardedIndex, mask (S,) int32) -> one (2, num_rows)
    int32 limb array; combine as (out[1].astype(int64) << 16) + out[0]
    on the host (one array = one relay readback, like combine_count).
    This is the device half of served TopN: the host applies threshold /
    candidate-id / n semantics to the exact totals (reference
    fragment.go:493-625 + executor.go:273-310 collapse into one
    collective + a host sort).
    """
    one = partial(_row_counts_one_slice, num_rows)

    def per_shard(keys, words, mask):
        local = jax.vmap(one)(keys, words)  # (S_local, R) int32
        local = jnp.where(mask[:, None] != 0, local, 0)
        lo = lax.psum((local & 0xFFFF).sum(axis=0), SLICE_AXIS)
        hi = lax.psum((local >> 16).sum(axis=0), SLICE_AXIS)
        return jnp.stack([lo, hi])

    fn = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(SLICE_AXIS), P(SLICE_AXIS), P(SLICE_AXIS)),
        out_specs=P(),
    )

    @jax.jit
    def run(index: ShardedIndex, mask):
        return fn(index.keys, index.words, mask)

    return run


def pack_mutation_batches(per_slice, num_slices: int, capacity: int):
    """Stack per-slice plan_slice_mutations outputs into padded (S, B)
    batch arrays for compile_serve_apply_writes.

    per_slice: {slice_id: (slot, word, set_mask, clear_mask)}. The
    no-op/width scheme is ops.pool's (pad_mutation_plan): padding rides
    out-of-bounds slots, B is the shared power-of-two width of the
    widest slice's plan.
    """
    from ..ops.pool import mutation_batch_width, pad_mutation_plan

    widest = max((len(v[0]) for v in per_slice.values()), default=0)
    b = mutation_batch_width(widest)
    empty = pad_mutation_plan(
        (np.zeros(0, np.int32), np.zeros(0, np.int32),
         np.zeros(0, np.uint32), np.zeros(0, np.uint32)), capacity, b)
    rows = [per_slice.get(si) for si in range(num_slices)]
    padded = [pad_mutation_plan(r, capacity, b) if r is not None else empty
              for r in rows]
    return tuple(np.stack([p[i] for p in padded]) for i in range(4))


def compile_serve_apply_writes(mesh: Mesh):
    """Jit the scatter of folded set/clear batches into sharded pools.

    fn(index, slot, word, set_mask, clear_mask) -> updated ShardedIndex.
    Targets are unique per slice (plan_slice_mutations) and padding
    rides out-of-bounds slots dropped by the scatter, so the update is
    exact for mixed sets and clears — the device-side half of SetBit /
    ClearBit (reference fragment.go:371-459), applied as one batched
    scatter per refresh instead of a full pool re-upload.
    """

    from ..ops.pool import scatter_words

    def per_shard(keys, words, slot, word, set_mask, clear_mask):
        return keys, jax.vmap(scatter_words)(
            words, slot, word, set_mask, clear_mask)

    fn = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(SLICE_AXIS),) * 6,
        out_specs=(P(SLICE_AXIS), P(SLICE_AXIS)),
    )

    @jax.jit
    def run(index: ShardedIndex, slot, word, set_mask, clear_mask):
        keys, words = fn(index.keys, index.words, slot, word,
                         set_mask, clear_mask)
        return ShardedIndex(keys=keys, words=words)

    return run


def default_mesh(n_devices: Optional[int] = None) -> Mesh:
    """A 1-D mesh over the first n (default: all) local devices."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (SLICE_AXIS,))


def sharded_index_from_holder(holder, index: str, frame: str,
                              view: str = "standard",
                              mesh: Optional[Mesh] = None,
                              max_slice: Optional[int] = None):
    """Stage a live frame view's fragments into a mesh-sharded device
    index.

    The H2D bridge between the host data model (Holder > ... > Fragment,
    reference fragment.go mmap-resident storage) and the device
    execution path: every slice 0..max_slice of (index, frame, view) is
    stacked into one ShardedIndex (absent fragments become empty
    shards), sharded over the mesh's slice axis. Returns
    (ShardedIndex, row_ids, staged_slices): row_ids translates real row
    ids to the dense indices compile_mesh_count/compile_mesh_topn use;
    staged_slices is the UNPADDED slice count (the returned
    sharded.num_slices is padded up to a mesh-axis multiple).

    This is the explicit-staging answer to the reference's O(1) mmap
    open (SURVEY.md §7 hard parts): call it once per epoch of queries,
    not per query, and re-stage after bulk writes.

    Only LOCALLY-present fragments are staged: the default max_slice is
    the highest local fragment of (frame, view) — not Index.max_slice(),
    which includes peer-owned slices that would stage as silent zero
    shards on a clustered holder. For a cluster-wide device index,
    stage per node and reduce, or pass max_slice explicitly after
    fetching remote fragments. A view with no fragments yet stages one
    empty shard; a missing index or frame raises KeyError.
    """
    idx_obj = holder.index(index)
    if idx_obj is None:
        raise KeyError(f"index not found: {index}")
    if idx_obj.frame(frame) is None:
        raise KeyError(f"frame not found: {index}/{frame}")
    if max_slice is None:
        v = holder.view(index, frame, view)
        max_slice = v.max_slice() if v is not None else 0
    bitmaps = []
    for s in range(max_slice + 1):
        frag = holder.fragment(index, frame, view, s)
        if frag is None:
            bitmaps.append(None)
            continue
        with frag._mu:
            frag.ensure_loaded()  # lazily-opened fragments parse here
            bitmaps.append(frag.storage)
    sharded, row_ids = build_sharded_index(bitmaps, mesh)
    return sharded, row_ids, len(bitmaps)


def connect_distributed(coordinator_address: Optional[str] = None,
                        num_processes: Optional[int] = None,
                        process_id: Optional[int] = None,
                        heartbeat_timeout_seconds: Optional[int] = None
                        ) -> int:
    """Join this host to the multi-host JAX runtime (the data plane's
    answer to the reference's multi-node HTTP query fan-out).

    After every participating host calls this, jax.devices() — and so
    default_mesh() — spans ALL hosts' chips: the same compile_mesh_*
    computations shard over the global slice axis, with psum riding ICI
    within a pod slice and DCN across hosts, no application-level RPC.
    The host-side control plane (schema broadcast, membership — gossip
    or HTTP) stays as-is; only bulk query compute moves to the global
    mesh. Arguments default to the JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID environment variables (read
    here — jax itself only honors the first), then to JAX's own
    TPU/Slurm/MPI cluster auto-detection.

    Returns this process's index. Call once, before any backend use.
    """
    import os

    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    kw = {}
    if heartbeat_timeout_seconds is None and os.environ.get(
            "PILOSA_TPU_HEARTBEAT_TIMEOUT_S"):
        heartbeat_timeout_seconds = int(
            os.environ["PILOSA_TPU_HEARTBEAT_TIMEOUT_S"])
    if heartbeat_timeout_seconds is not None:
        # Rank-death detection bound: a died peer surfaces as a
        # coordination error on the survivors within this window
        # instead of wedging the next collective indefinitely.
        kw["heartbeat_timeout_seconds"] = heartbeat_timeout_seconds
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id, **kw)
    return jax.process_index()
